"""Local single-process executor — the MiniCluster analog.

reference: runtime/minicluster/MiniCluster.java runs the whole control plane
in one JVM for tests; the per-task engine is the mailbox loop
(streaming/runtime/tasks/StreamTask.java:916 + MailboxProcessor.java:214).

Re-design: one Python thread owns the whole dataflow (single-owner discipline
— the mailbox model without the mailbox). Sources are polled round-robin into
micro-batches; each batch is pushed depth-first through the operator DAG;
watermarks are merged per multi-input operator via WatermarkValve. Operator
"chaining" is implicit (direct method calls); the heavy per-batch math inside
WindowAggOperator is the jitted device code. Checkpoint barriers are batch
boundaries: the executor simply snapshots all operators between pushes
(alignment is structural — SURVEY.md §7 step 6).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from flink_tpu.core.config import (
    BatchOptions,
    CheckpointOptions,
    Configuration,
    CoreOptions,
    DeploymentOptions,
    LatencyOptions,
    StateOptions,
)
from flink_tpu.chaos import injection as chaos
from flink_tpu.core.records import RecordBatch
from flink_tpu.observe import flight_recorder as flight
from flink_tpu.graph.transformations import StreamGraph, Transformation
from flink_tpu.runtime.elements import MAX_WATERMARK, Watermark
from flink_tpu.runtime.operators import Operator, OperatorContext
from flink_tpu.runtime.process import TaggedBatch
from flink_tpu.runtime.watermarks import WatermarkValve


from flink_tpu.core.annotations import internal

class _Node:
    __slots__ = ("transformation", "operator", "valve", "children",
                 "child_input_idx", "records_in", "records_out", "held_wm",
                 "busy_s", "marker_hist")

    def __init__(self, transformation: Transformation,
                 operator: Optional[Operator]):
        self.transformation = transformation
        self.operator = operator
        self.valve = WatermarkValve(max(len(transformation.inputs), 1))
        self.children: List[_Node] = []
        self.child_input_idx: List[int] = []
        self.records_in = 0
        self.records_out = 0
        #: wall time spent inside THIS operator's batch/watermark hooks
        #: (excludes downstream forwarding) — the DS2 busy-fraction
        #: numerator the autoscale policy differentiates
        self.busy_s = 0.0
        #: watermark held back while the operator has in-flight async
        #: fires — forwarded downstream only after their results are
        #: (see _drain_pending; reference: watermark must not overtake
        #: the records it covers)
        self.held_wm: Optional[int] = None
        #: per-operator LatencyMarker histogram (observe.export) — the
        #: executor stamps each source batch and records marker->here
        self.marker_hist = None


class JobCancelledError(RuntimeError):
    """Raised inside the task loop when the job is cancelled externally."""


class _ControlRequest:
    """Completion plumbing shared by all task-loop control requests: the
    loop completes them via ``finish(result, error)``, the client blocks in
    ``wait`` — one contract, relied on by _fail_pending_controls."""

    timeout_message = "control request not served"

    def __init__(self):
        import threading

        self.result = None
        self.error: Optional[BaseException] = None
        self._done = threading.Event()

    def finish(self, result, error=None) -> None:
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(self.timeout_message)
        if self.error is not None:
            raise self.error
        return self.result


class StateQueryRequest(_ControlRequest):
    """Queryable-state point lookup served at a batch boundary — the
    single-owner loop means reads never race task-thread mutations
    (reference: flink-queryable-state KvStateServer, but without the
    concurrent-read hazards of its direct backend access)."""

    timeout_message = "state query not served"

    def __init__(self, operator_name: str, key, namespace=None):
        super().__init__()
        self.operator_name = operator_name
        self.key = key
        self.namespace = namespace


class StateQueryBatchRequest(_ControlRequest):
    """Batched queryable-state lookup: ALL keys served in one pass —
    one gather program + ONE device read for the whole batch (the
    serving-plane contract; the one-RTT-per-key path is gone). The
    single-key StateQueryRequest is now a thin wrapper over this."""

    timeout_message = "state query batch not served"

    def __init__(self, operator_name: str, keys, namespace=None):
        super().__init__()
        self.operator_name = operator_name
        self.keys = list(keys)
        self.namespace = namespace


class RescaleRequest(_ControlRequest):
    """Cross-job shard arbitration lands here: the tenancy arbiter posts
    its per-job allocation, the task loop serves it at a batch boundary
    (pending fires drained first — their buffers reference the
    pre-reshard plane) and drives the operator's LIVE ``reshard``."""

    timeout_message = "rescale not served"

    def __init__(self, new_shards: int):
        super().__init__()
        self.new_shards = int(new_shards)


class SavepointRequest(_ControlRequest):
    """A user-triggered savepoint (optionally stop-with-savepoint).

    reference: CheckpointCoordinator.triggerSavepoint + the
    stop-with-savepoint flow (runtime/scheduler/stopwithsavepoint/*).
    Served by the task loop at a batch boundary — the structurally aligned
    barrier point of the micro-batch engine.
    """

    def __init__(self, path: str, stop: bool = False, drain: bool = False):
        super().__init__()
        self.path = path
        self.stop = stop
        self.drain = drain
        self.timeout_message = f"savepoint {path!r} not completed"

    @property
    def result_path(self) -> Optional[str]:
        return self.result


class _SourcePump:
    """Bounded-prefetch source reader: a thread that polls one source,
    assigns timestamps and watermarks, and hands (batch, watermark,
    position) entries to the task loop through a bounded queue.

    The queue bound IS the backpressure (credit-based flow control,
    reference: RemoteInputChannel.java:114 unannouncedCredit — here a
    credit is a queue slot). Each entry carries the source position taken
    AFTER that batch, so a checkpoint cut at batch boundary N snapshots
    exactly the consumed prefix — prefetched-but-unprocessed batches are
    re-read after restore (reference: source offsets ride the same barrier
    as operator state).

    The pump owns the source object while running (single-owner
    discipline); the task loop touches the source only after ``stop()``.
    """

    _EOS = object()

    def __init__(self, transformation, batch_size: int, in_flight: int):
        import queue as _q
        import threading

        self.t = transformation
        self.batch_size = batch_size
        self.queue: "_q.Queue" = _q.Queue(maxsize=max(in_flight, 1))
        self.wm_gen = transformation.watermark_strategy.create()
        self._stop = threading.Event()    # stop reading new batches
        self._abort = threading.Event()   # discard mode: puts give up
        self.error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name=f"source-pump-{transformation.name}",
            daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _put(self, item) -> bool:
        # an already-polled batch advanced the source position, so it must
        # reach the consumer unless the job is abandoning data outright
        # (_abort); a mere stop_filling keeps trying while the drain path
        # consumes
        import queue as _q

        while not self._abort.is_set():
            try:
                self.queue.put(item, timeout=0.05)
                return True
            except _q.Full:
                continue
        return False

    def _run(self) -> None:
        src = self.t.source
        strategy = self.t.watermark_strategy
        try:
            while not self._stop.is_set():
                # batch_size is re-read each poll: the adaptive controller
                # on the task loop may resize it (benign cross-thread read)
                batch = src.poll_batch(self.batch_size)
                if batch is None:
                    self._put((self._EOS, None, src.snapshot_position()))
                    return
                if len(batch) == 0:
                    continue
                batch = strategy.assign_timestamps(batch)
                wm = self.wm_gen.on_batch(batch)
                pos = src.snapshot_position()
                if not self._put((batch, wm, pos)):
                    return
        except BaseException as e:  # noqa: BLE001 - surfaced to task loop
            self.error = e
            self._put((self._EOS, None, None))

    def poll(self, timeout: float = 0.0):
        """One queue entry or None. Raises the pump's error, if any."""
        import queue as _q

        try:
            entry = self.queue.get(timeout=timeout) if timeout \
                else self.queue.get_nowait()
        except _q.Empty:
            return None
        if entry[0] is self._EOS and self.error is not None:
            raise self.error
        return entry

    def stop_filling(self) -> None:
        """Stop reading new batches; already-queued entries stay consumable
        (the drain path processes them before the final snapshot)."""
        self._stop.set()

    def consume_remaining(self):
        """Yield the queued entries after ``stop_filling`` until the pump
        thread has exited and the queue is empty."""
        import queue as _q

        while self._thread.is_alive() or not self.queue.empty():
            try:
                yield self.queue.get(timeout=0.05)
            except _q.Empty:
                continue

    def stop(self) -> None:
        """Hard stop: discard prefetched entries (no-drain paths — the
        consumed-prefix position makes dropped entries re-readable)."""
        self._stop.set()
        self._abort.set()
        import queue as _q

        try:
            while True:
                self.queue.get_nowait()
        except _q.Empty:
            pass
        self._thread.join(timeout=5)


class JobHandle:
    """Setup artifacts of one stepwise job run — the first value yielded
    by :meth:`LocalExecutor.run_stepwise`. The tenancy session cluster
    uses it to bind per-job quotas to the stateful operators, register
    the job's row in the ``tenancy`` metric group, and read the
    fairness/arbitration signals (busy time, backlog, resident rows)."""

    def __init__(self, job_name, graph, nodes, registry, traces,
                 job_group, pumps, sources, watchdog=None):
        self.job_name = job_name
        self.graph = graph
        self.nodes = nodes
        self.registry = registry
        self.traces = traces
        self.job_group = job_group
        self.pumps = pumps
        self.sources = sources
        #: the job's DeviceWatchdog when watchdog.enabled (None
        #: otherwise) — the tenancy arbiter reads its quarantine count
        #: to shrink the cross-job shard budget
        self.watchdog = watchdog

    def stateful_operators(self):
        """Operators owning keyed device state (spill_counters is the
        capability marker the metric tree already keys on)."""
        return [n.operator for n in self.nodes.values()
                if n.operator is not None
                and hasattr(n.operator, "spill_counters")]

    def busy_ms(self) -> float:
        """Wall time spent inside this job's operator hooks — the per-job
        ``busyTimeMsTotal`` the deficit-round-robin scheduler reports."""
        return sum(n.busy_s for n in self.nodes.values()) * 1000.0

    def backlog_records(self) -> int:
        """Prefetched-but-unprocessed records in the job's pump queues
        (the arbitration demand signal)."""
        return sum(p.queue.qsize() * p.batch_size
                   for p in self.pumps.values())

    def resident_rows(self) -> int:
        """Device-resident state rows across the job's engines."""
        return sum(sum(op.shard_resident_rows())
                   for op in self.stateful_operators()
                   if hasattr(op, "shard_resident_rows"))


@internal
class LocalExecutor:
    def __init__(self, config: Optional[Configuration] = None):
        self.config = config or Configuration()

    def run(self, graph: StreamGraph, job_name: str = "job",
            restore_from: Optional[str] = None, cancel_event=None,
            restore_mode="no-claim", control_queue=None):
        """Execute the graph to completion.

        Checkpointing: between two source polls the whole dataflow is
        quiescent (single-owner loop), so a snapshot taken there is a
        perfectly aligned barrier (reference: CheckpointBarrierHandler
        alignment, made structural by the micro-batch design). Sources
        snapshot their positions in the same cut, giving exactly-once state
        on restore.
        """
        gen = self.run_stepwise(graph, job_name, restore_from,
                                cancel_event, restore_mode, control_queue)
        try:
            while True:
                next(gen)
        except StopIteration as done:
            return done.value

    def run_stepwise(self, graph: StreamGraph, job_name: str = "job",
                     restore_from: Optional[str] = None, cancel_event=None,
                     restore_mode="no-claim", control_queue=None,
                     cooperative: bool = False):
        """Generator form of :meth:`run` — the multi-tenant scheduling
        surface. First yields a :class:`JobHandle` (setup artifacts: the
        tenancy session cluster binds quotas and metric gauges through
        it), then yields the number of source records processed per loop
        iteration (the deficit-round-robin accounting unit); the
        StopIteration value is the JobExecutionResult.

        ``cooperative=True`` skips the idle 1 ms sleep — the hosting
        scheduler owns pacing, and one starved job must not stall its
        siblings' quanta. Closing/throwing into the generator runs the
        same resource-release path an in-loop failure does."""
        from flink_tpu.datastream.environment import JobExecutionResult

        #: chaos context: fault plans on a multi-job cluster can target
        #: ONE tenant (where={"job": ...}) — the executor is per-job
        self._chaos_job = job_name

        from flink_tpu.core.config import ExecutionModeOptions

        batch_size = self.config.get(BatchOptions.BATCH_SIZE)
        max_parallelism = self.config.get(CoreOptions.MAX_PARALLELISM)
        # stateplane.backend.<family>=pallas|xla: applied (and validated
        # LOUDLY — unknown family/backend fails at submit, not mid-run)
        # before any engine builds a program; backend selection is
        # process-global, like the program cache the keys live in
        from flink_tpu.stateplane import configure_backends

        configure_backends(self.config)
        ckpt_interval = self.config.get(CheckpointOptions.INTERVAL_MS)
        ckpt_every_n = self.config.get(CheckpointOptions.EVERY_N_BATCHES)
        ckpt_dir = self.config.get(StateOptions.CHECKPOINT_DIR)
        # bounded/batch mode: no intermediate watermarks — every window
        # and aggregate fires exactly once at end-of-input (reference:
        # RuntimeExecutionMode.BATCH; the MAX watermark at source
        # exhaustion is the single "end of time" event)
        batch_mode = self.config.get(
            ExecutionModeOptions.RUNTIME_MODE) == "batch"
        if batch_mode:
            for t in graph.sources:
                if not getattr(t.source, "bounded", True):
                    raise RuntimeError(
                        "execution.runtime-mode=batch requires bounded "
                        f"sources; {t.name!r} is unbounded (reference: "
                        "batch mode rejects unbounded sources)")
        storage = None
        if ckpt_dir and (ckpt_interval or ckpt_every_n):
            from flink_tpu.checkpoint.storage import CheckpointStorage

            storage = CheckpointStorage(
                ckpt_dir,
                compress=self.config.get(CheckpointOptions.COMPRESSION))

        # metrics + traces (reference: MetricRegistryImpl + Span reporting;
        # standard task I/O metric names follow the reference's
        # numRecordsIn/Out, currentInputWatermark conventions)
        from flink_tpu.metrics import MetricRegistry, TraceCollector

        registry = MetricRegistry()
        traces = TraceCollector()
        job_group = registry.root_group("job", job_name)
        # chaos counters ride the job's metric tree when a fault plan is
        # armed (job.<name>.chaos.faults_injected / retries / recoveries)
        chaos.register_chaos_metrics(job_group)
        # flight recorder: name the job for every span the task loop
        # (and the engines it drives) records, wire the jax-level probes
        # (XLA backend compiles, D2H materializations) into the same
        # timeline, and surface per-span-kind duration aggregates on
        # the job metric tree
        from flink_tpu.observe import install_probes
        from flink_tpu.observe.export import (
            LatencyMarkerPlane,
            register_flight_metrics,
        )

        install_probes()
        flight.set_job(job_name)
        # the flight aggregates are PROCESS-global (the recorder is
        # shared by every job on the mesh), so they register at the
        # registry root, not under this job's scope — a per-job scope
        # would claim other tenants' spans as this job's
        register_flight_metrics(registry.root_group())
        # event-time latency markers: each source batch is the marker;
        # per-operator marker histograms + watermark-lag gauges land
        # under job.<name>.<op>.latency
        lat_plane = self._lat_plane = LatencyMarkerPlane()
        # device watchdog (watchdog.enabled): one per job, attached to
        # every mesh engine through the operator context; heartbeat
        # gauges under job.<name>.watchdog. A ShardFailedError it raises
        # surfaces through the normal failure path (restart strategy ->
        # restore) — the SHARD-granular recovery protocol itself is the
        # chaos harness's run_shard_loss_verify (see README "Failure
        # domains").
        from flink_tpu.runtime.watchdog import watchdog_from_config

        watchdog = watchdog_from_config(
            self.config, self.config.get(CoreOptions.DEFAULT_PARALLELISM))
        if watchdog is not None:
            watchdog.register_metrics(job_group)

        # build nodes
        nodes: Dict[int, _Node] = {}
        default_par = self.config.get(CoreOptions.DEFAULT_PARALLELISM)
        memory_manager = None
        device_budget = self.config.get(StateOptions.DEVICE_MEMORY_BUDGET)
        if device_budget:
            from flink_tpu.core.memory import MemoryManager

            # ONE managed pool for the whole job: every stateful
            # operator's device footprint reserves from it (reference:
            # MemoryManager.java per-slot managed memory)
            memory_manager = MemoryManager(device_budget)
        for t in graph.nodes:
            op = t.operator_factory() if t.operator_factory else None
            node = _Node(t, op)
            if op is not None:
                # explicit set_parallelism wins; otherwise keyed operators
                # pick up parallelism.default (the mesh size of the
                # key-group axis — reference: env default parallelism
                # applied at StreamGraph generation)
                par = t.parallelism if t.parallelism else (
                    default_par if t.keyed else 1)
                ctx = OperatorContext(operator_index=0, parallelism=par,
                                      max_parallelism=max_parallelism,
                                      async_fires=self.config.get(
                                          BatchOptions.ASYNC_FIRES),
                                      max_dispatch_ahead=self.config.get(
                                          BatchOptions.MAX_DISPATCH_AHEAD),
                                      memory_manager=memory_manager,
                                      shuffle_mode=self.config.get(
                                          DeploymentOptions.SHUFFLE_MODE),
                                      host_topology=(self.config.get(
                                          DeploymentOptions.SHUFFLE_HOSTS)
                                          or None),
                                      watchdog=watchdog,
                                      pane_preagg=self.config.get(
                                          LatencyOptions.PANE_PREAGG))
                op.open(ctx)
            nodes[t.uid] = node
            g = job_group.add_group(f"{t.name}#{t.uid}")
            g.gauge("numRecordsIn", lambda n=node: n.records_in)
            g.gauge("numRecordsOut", lambda n=node: n.records_out)
            g.gauge("currentInputWatermark",
                    lambda n=node: n.valve.combined)
            g.gauge("busyTimeMsTotal", lambda n=node: n.busy_s * 1000.0)
            if op is not None:
                # LatencyMarker surface: marker histogram + watermark
                # lag vs the sources' frontier, under <op>.latency
                node.marker_hist = lat_plane.operator_group(
                    g, f"{t.name}#{t.uid}",
                    lambda n=node: n.valve.combined)
            if op is not None and hasattr(op, "spill_counters"):
                # the `state` group: the same numbers spill_counters()
                # reports, on the metric tree the autoscaler reads
                counters = op.spill_counters()
                if counters is not None:
                    sg = g.add_group("state")
                    for cname in counters:
                        sg.gauge(cname,
                                 lambda o=op, c=cname:
                                 (o.spill_counters() or {}).get(c, 0))
                    sg.gauge("resident_rows_per_shard",
                             lambda o=op: list(o.shard_resident_rows()))
                    sg.gauge("resident_rows",
                             lambda o=op: sum(o.shard_resident_rows()))
                    sg.gauge("key_imbalance",
                             lambda o=op: o.key_imbalance())
            if op is not None and hasattr(op, "fire_latencies_ms"):
                from flink_tpu.metrics.core import quantile_sorted

                # the `window` group: live fire-latency percentiles per
                # stateful operator, fed from the SAME bounded reservoir
                # the bench and the job result read — the latency tier's
                # observable surface (KNOWN_METRIC_GROUPS discipline;
                # supersedes the old top-level windowFireLatencyP99Ms
                # gauge, which had no consumers)
                wg = g.add_group("window")
                wg.gauge("fireLatencyP50Ms",
                         lambda o=op: quantile_sorted(
                             sorted(o.fire_latencies_ms), 0.5))
                wg.gauge("fireLatencyP99Ms",
                         lambda o=op: quantile_sorted(
                             sorted(o.fire_latencies_ms), 0.99))
                wg.gauge("fireCount",
                         lambda o=op: getattr(
                             o, "fires_total",
                             len(o.fire_latencies_ms)))
            if op is not None and hasattr(op, "late_records_dropped"):
                g.gauge("numLateRecordsDropped",
                        lambda o=op: o.late_records_dropped)
        for t in graph.nodes:
            n = nodes[t.uid]
            for child_t in graph.children(t):
                n.children.append(nodes[child_t.uid])
                n.child_input_idx.append(
                    graph.input_index(t, child_t))

        sources = [(t, nodes[t.uid]) for t in graph.sources]
        generators = {}
        for t, _ in sources:
            t.source.open(0, 1)
            generators[t.uid] = t.watermark_strategy.create()
        in_flight = self.config.get(BatchOptions.IN_FLIGHT_BATCHES)
        latency_target = self.config.get(BatchOptions.LATENCY_TARGET_MS)
        #: fire-deadline-aware micro-batching (latency.fire-deadline-ms):
        #: ingest batches split against the budget using the measured
        #: per-record step rate, with landed fires harvested between the
        #: splits — a due fire never waits out a full batch dispatch
        self._fire_deadline_ms = self.config.get(
            LatencyOptions.FIRE_DEADLINE_MS)
        self._deadline_rate = 0.0  # EMA of records/s through the dataflow
        debloater = None
        if latency_target > 0:
            from flink_tpu.runtime.debloater import BatchSizeController

            debloater = BatchSizeController(
                initial=batch_size,
                min_size=self.config.get(BatchOptions.MIN_BATCH_SIZE),
                max_size=batch_size,
                target_latency_ms=latency_target)
            batch_size = debloater.size

        checkpoint_count = 0
        claimed = None
        if restore_from is not None:
            from flink_tpu.checkpoint.savepoint import prepare_restore
            from flink_tpu.checkpoint.storage import (
                read_checkpoint_chain,
                read_manifest,
            )

            with flight.span("checkpoint.restore"), \
                    traces.span("recovery", "restore") as rsp:
                snap_dir, claimed = prepare_restore(
                    restore_from, restore_mode,
                    own_checkpoint_root=ckpt_dir)
                states = read_checkpoint_chain(snap_dir)
                self._restore_all(graph, nodes, states)
                rsp.set_attribute("snapshot", snap_dir)
                rsp.set_attribute("operators", len(states))
            checkpoint_count = int(read_manifest(snap_dir)["checkpoint_id"])
            restored_id = checkpoint_count
            # a valid delta base is the job's OWN chk-<id> directory — a
            # savepoint that merely lives inside the root is NOT one (its
            # id would alias an unrelated sibling checkpoint)
            restored_in_root = bool(ckpt_dir) and (
                os.path.dirname(os.path.abspath(snap_dir))
                == os.path.abspath(ckpt_dir)) and (
                os.path.basename(snap_dir) == f"chk-{restored_id}")
            if storage is not None:
                # the checkpoint root may hold higher-numbered checkpoints
                # from an abandoned timeline (restore from an older
                # savepoint): keep ids monotonic so new checkpoints
                # supersede the stale ones instead of being retain()-ed away
                checkpoint_count = max(
                    checkpoint_count, storage.latest_checkpoint_id() or 0)

        t0 = time.perf_counter()
        total_records = 0
        last_ckpt = time.time() * 1000
        batches_since_ckpt = 0
        incremental = self.config.get(CheckpointOptions.INCREMENTAL)
        full_every = max(self.config.get(CheckpointOptions.FULL_EVERY), 1)
        # deltas may build on a restored checkpoint only when it lives in
        # the job's own checkpoint root (its chain stays intact under
        # retain()); savepoints / foreign artifacts are not valid bases
        last_written_id = None
        since_full = 0
        if restore_from is not None and storage is not None and \
                restored_in_root:
            last_written_id = restored_id

        active = {t.uid for t, _ in sources}
        # host/device overlap: pump threads poll + timestamp the NEXT
        # batches while this loop drives slot lookups and (async-dispatched)
        # device kernels for the current one; the bounded queue is the
        # backpressure (reference: AsyncExecutionController.java:57 overlap,
        # RemoteInputChannel credit flow). Positions consumed so far are
        # tracked per source so checkpoint cuts stay exactly aligned.
        pumps: Dict[int, _SourcePump] = {}
        source_positions: Dict[int, Any] = {
            t.uid: t.source.snapshot_position() for t, _ in sources}
        if in_flight > 0:
            for t, _ in sources:
                pumps[t.uid] = _SourcePump(t, batch_size, in_flight)
            for p in pumps.values():
                p.start()
        # backlog signal: records prefetched-but-unprocessed in the pump
        # queues (the credit-based flow-control depth, estimated from
        # queued batches x current batch size) — feeds the autoscaler
        job_group.gauge(
            "sourceBacklogRecordsEstimate",
            lambda: sum(p.queue.qsize() * p.batch_size
                        for p in pumps.values()))
        autoscale = self._setup_autoscale(nodes, job_group, pumps,
                                          watchdog=watchdog)
        # wall-clock tick targets (processing-time windows/timers)
        pt_nodes = [n for n in nodes.values()
                    if n.operator is not None
                    and getattr(n.operator, "uses_processing_time", False)]
        try:
            yield JobHandle(job_name=job_name, graph=graph, nodes=nodes,
                            registry=registry, traces=traces,
                            job_group=job_group, pumps=pumps,
                            sources=sources, watchdog=watchdog)
            while active:
                step_records = 0
                if cancel_event is not None and cancel_event.is_set():
                    raise JobCancelledError(job_name)
                # harvest landed async fires + release held watermarks
                # (cheap is_ready() polls when nothing is pending)
                self._drain_pending(nodes)
                if autoscale is not None:
                    autoscale.tick()
                if pt_nodes:
                    now_ms = int(time.time() * 1000)
                    for n in pt_nodes:
                        for out in n.operator.on_processing_time(now_ms):
                            self._forward(n, out)
                progressed = False
                for t, node in sources:
                    if t.uid not in active:
                        continue
                    if pumps:
                        entry = pumps[t.uid].poll(
                            timeout=0.002 if not progressed else 0.0)
                        if entry is None:
                            continue
                        batch, wm, pos = entry
                        if batch is _SourcePump._EOS:
                            active.discard(t.uid)
                            if pos is not None:
                                source_positions[t.uid] = pos
                            self._emit_watermark(node, MAX_WATERMARK)
                            t.source.close()
                            continue
                    else:
                        batch = t.source.poll_batch(batch_size)
                        if batch is None:
                            active.discard(t.uid)
                            self._emit_watermark(node, MAX_WATERMARK)
                            t.source.close()
                            continue
                        if len(batch) == 0:
                            continue
                        batch = t.watermark_strategy.assign_timestamps(batch)
                        wm = generators[t.uid].on_batch(batch)
                        pos = t.source.snapshot_position()
                    progressed = True
                    batches_since_ckpt += 1
                    total_records += len(batch)
                    step_records += len(batch)
                    source_positions[t.uid] = pos
                    tb = time.perf_counter() if debloater else 0.0
                    # this batch IS the latency marker: stamp its ingest
                    # wall time; operators record marker->here as the
                    # depth-first push reaches them, and the marker dies
                    # with the push — later drains/flushes are not this
                    # batch's latency
                    lat_plane.stamp_source()
                    if wm is not None and not batch_mode:
                        lat_plane.note_source_watermark(int(wm),
                                                        source=t.uid)
                    try:
                        if self._fire_deadline_ms > 0 and not batch_mode:
                            self._emit_deadline_split(node, batch,
                                                      nodes, wm)
                        else:
                            self._emit_batch(node, batch)
                            if wm is not None and not batch_mode:
                                self._emit_watermark(node, wm)
                    finally:
                        lat_plane.end_marker()
                    if debloater is not None:
                        new_size = debloater.observe(
                            len(batch), time.perf_counter() - tb)
                        if new_size != batch_size:
                            batch_size = new_size
                            for p in pumps.values():
                                p.batch_size = new_size
                if storage is not None:
                    due = (ckpt_every_n
                           and batches_since_ckpt >= ckpt_every_n) or (
                        not ckpt_every_n and ckpt_interval
                        and time.time() * 1000 - last_ckpt >= ckpt_interval)
                    if due:
                        checkpoint_count += 1
                        use_delta = (incremental and last_written_id
                                     is not None
                                     and since_full < full_every)
                        # in-flight fire results must reach their sinks
                        # before the cut — the bookkeeper already marked
                        # those windows fired, so a snapshot without them
                        # would lose results on restore
                        self._drain_pending(nodes, wait=True)
                        with flight.span("checkpoint.write"), traces.span(
                                "checkpoint",
                                f"checkpoint-{checkpoint_count}") as sp:
                            snap = self.snapshot_all(graph, nodes,
                                                     source_positions,
                                                     delta=use_delta)
                            extra = ({"incremental": True,
                                      "base": last_written_id}
                                     if use_delta else None)
                            new_dir = storage.write_checkpoint(
                                checkpoint_count, job_name, snap,
                                extra=extra)
                            sp.set_attribute("checkpointId", checkpoint_count)
                            sp.set_attribute("incremental", use_delta)
                            sp.set_attribute("stateSizeBytes", sum(
                                e.stat().st_size
                                for e in os.scandir(new_dir) if e.is_file()))
                        last_written_id = checkpoint_count
                        since_full = since_full + 1 if use_delta else 1
                        if claimed is not None:
                            claimed.on_checkpoint_complete(new_dir)
                        # checkpoint durable -> two-phase sinks publish
                        # (reference: notifyCheckpointComplete -> commit)
                        for node in nodes.values():
                            op = node.operator
                            if op is not None and hasattr(
                                    op, "notify_checkpoint_complete"):
                                op.notify_checkpoint_complete(
                                    checkpoint_count)
                        storage.retain(self._retained())
                        last_ckpt = time.time() * 1000
                        batches_since_ckpt = 0
                if control_queue is not None:
                    stopped = self._serve_control(
                        control_queue, graph, nodes, sources, active,
                        job_name, checkpoint_count, traces,
                        source_positions, pumps)
                    if stopped is not None:
                        suppress_final_drain = not stopped.drain
                        savepoint_path = stopped.result_path
                        break
                if not progressed and active and not pumps \
                        and not cooperative:
                    time.sleep(0.001)
                yield step_records
            else:
                suppress_final_drain = False
                savepoint_path = None

            # drain/close in topological order (skipped for
            # stop-with-savepoint without --drain: state was saved, in-flight
            # windows intentionally not fired — they resume from the
            # savepoint)
            self._drain_pending(nodes, wait=True)
            if not suppress_final_drain:
                for t in graph.nodes:
                    node = nodes[t.uid]
                    if node.operator is not None:
                        for out in node.operator.close():
                            self._forward(node, out)
            else:
                # no-drain stop still releases resources and flushes sinks —
                # dispose() never emits (reference: Task releaseResources)
                for node in nodes.values():
                    if node.operator is not None:
                        try:
                            node.operator.dispose()
                        except Exception:
                            pass
            self._fail_pending_controls(
                control_queue, f"job {job_name!r} already terminated")
        except BaseException:
            # failure/cancel path: release resources without emitting
            # (reference: Task.doRun finally -> cancel + releaseResources)
            for p in pumps.values():
                try:
                    p.stop()
                except Exception:
                    pass
            for t, _ in sources:
                try:
                    t.source.close()
                except Exception:
                    pass
            for node in nodes.values():
                if node.operator is not None:
                    try:
                        node.operator.dispose()
                    except Exception:
                        pass
            self._fail_pending_controls(
                control_queue, f"job {job_name!r} terminated abnormally")
            raise

        elapsed = time.perf_counter() - t0
        fire_latencies: List[float] = []
        for node in nodes.values():
            lat = getattr(node.operator, "fire_latencies_ms", None)
            if lat:
                fire_latencies.extend(lat)  # deque -> list copy
        metrics = {
            "records_emitted_by_sources": total_records,
            "runtime_s": elapsed,
            **({"effective_batch_size": batch_size}
               if debloater is not None else {}),
            "records_per_s": total_records / elapsed if elapsed > 0 else 0.0,
            "checkpoints": checkpoint_count,
            **({"savepoint": savepoint_path} if savepoint_path else {}),
            "per_operator": {
                f"{n.transformation.name}#{uid}": {
                    "records_in": n.records_in, "records_out": n.records_out}
                for uid, n in nodes.items()
            },
        }
        if fire_latencies:
            from flink_tpu.metrics.core import quantile_sorted

            fire_latencies.sort()
            metrics["window_fire_latency_ms"] = {
                "p50": quantile_sorted(fire_latencies, 0.5),
                "p99": quantile_sorted(fire_latencies, 0.99),
                "max": fire_latencies[-1],
                "count": len(fire_latencies),
            }
        if getattr(self, "fallback_reason", None):
            # surfaced in REST job status: the user asked for stage
            # parallelism but opted into single-slot fallback
            metrics["stage_fallback"] = self.fallback_reason
        if autoscale is not None and autoscale.events:
            metrics["autoscale"] = {
                "rescales": len(autoscale.events),
                "live_handoffs": autoscale.live_handoffs,
                "path": [(e.source, e.target) for e in autoscale.events],
                "handoff_ms": [round(e.handoff_s * 1e3, 3)
                               for e in autoscale.events
                               if e.mode == "live"],
            }
        result = JobExecutionResult(job_name, metrics)
        result.registry = registry
        result.traces = traces
        return result

    def _retained(self) -> int:
        from flink_tpu.core.config import retained_checkpoints

        return retained_checkpoints(self.config)

    # ------------------------------------------------------------ autoscale

    def _setup_autoscale(self, nodes, job_group, pumps, watchdog=None):
        """Build the in-loop autoscale controller for the first keyed
        operator that supports LIVE reshard (mesh engine), when
        autoscale.enabled. The controller ticks at batch boundaries on
        the task loop — the single-owner point where migrating device
        state is race-free. A watchdog-quarantined (dead) shard shrinks
        the device budget: the policy must not scale onto a device that
        no longer answers."""
        from flink_tpu.core.config import AutoscaleOptions

        if not self.config.get(AutoscaleOptions.ENABLED):
            return None
        target = None
        for node in nodes.values():
            op = node.operator
            if op is not None and getattr(op, "supports_live_rescale",
                                          False):
                target = node
                break
        if target is None:
            return None
        import jax

        from flink_tpu.autoscale.controller import (
            AutoscaleController,
            SignalSample,
        )
        from flink_tpu.autoscale.policy import ScalingPolicy

        engine = target.operator.windower
        # clamp the configured bounds to what reshard() can actually do
        # (devices, the key-group space, the engine's owned range) — a
        # policy allowed to target beyond them would turn a load spike
        # into a ValueError on the task loop, i.e. a job crash
        max_shards = self.config.get(AutoscaleOptions.MAX_SHARDS) \
            or len(jax.devices())
        max_shards = min(max_shards, len(jax.devices()),
                         int(engine.max_parallelism))
        kgr = getattr(engine, "key_group_range", None)
        if kgr is not None:
            max_shards = min(max_shards, int(kgr[1]) - int(kgr[0]) + 1)
        min_shards = min(self.config.get(AutoscaleOptions.MIN_SHARDS),
                         max_shards)
        policy = ScalingPolicy(
            utilization_target=self.config.get(
                AutoscaleOptions.UTILIZATION_TARGET),
            hysteresis=self.config.get(AutoscaleOptions.HYSTERESIS),
            cooldown_s=self.config.get(
                AutoscaleOptions.COOLDOWN_MS) / 1000.0,
            min_shards=min_shards,
            max_shards=max_shards,
            imbalance_limit=self.config.get(
                AutoscaleOptions.IMBALANCE_LIMIT),
            # the fire-latency signal (second input next to backlog):
            # sustained p99 over the fire deadline scales UP and vetoes
            # scale-down, even when the rate signal reads steady
            fire_deadline_ms=self.config.get(
                LatencyOptions.FIRE_DEADLINE_MS),
            fire_breach_ticks=self.config.get(
                AutoscaleOptions.FIRE_BREACH_TICKS))

        _fire_seen = [0]  # fires_total at the previous sample

        def fire_p99(node=target):
            from flink_tpu.metrics.core import quantile_sorted

            op = node.operator
            lat = getattr(op, "fire_latencies_ms", None)
            if not lat:
                return 0.0
            # staleness guard: no NEW fires since the last sample means
            # no deadline misses NOW — a burst of old slow samples must
            # not keep the breach streak alive (and re-trigger a
            # scale-up after every cooldown) once fires stop or recover
            total = getattr(op, "fires_total", len(lat))
            if total == _fire_seen[0]:
                return 0.0
            _fire_seen[0] = total
            # recent window of the bounded reservoir: the signal must
            # track NOW, not the job's whole history
            return quantile_sorted(sorted(list(lat)[-256:]), 0.99)

        def sample(node=target):
            return SignalSample(
                records_total=node.records_in,
                busy_ms_total=node.busy_s * 1000.0,
                backlog=sum(p.queue.qsize() * p.batch_size
                            for p in pumps.values()),
                shard_resident_rows=node.operator.shard_resident_rows(),
                fire_latency_p99_ms=fire_p99())

        def apply(new_shards, node=target):
            # in-flight fires reference the pre-reshard device arrays —
            # the drain boundary is the same one checkpoints use
            self._drain_pending(nodes, wait=True)
            if watchdog is not None:
                # a dead shard changes the budget: never scale onto a
                # quarantined device
                new_shards = min(
                    new_shards, watchdog.available(len(jax.devices())))
            return node.operator.reshard(new_shards)

        return AutoscaleController(
            policy, sample_fn=sample, apply_fn=apply,
            current_shards_fn=lambda: int(target.operator.windower.P),
            interval_s=self.config.get(
                AutoscaleOptions.INTERVAL_MS) / 1000.0,
            metrics_group=job_group)

    # -------------------------------------------------------------- control

    def _serve_control(self, control_queue, graph, nodes, sources, active,
                       job_name: str, checkpoint_id: int, traces,
                       source_positions, pumps):
        """Serve pending SavepointRequests at a batch boundary. Returns the
        request if it asked the job to stop, else None."""
        import queue as _queue

        from flink_tpu.checkpoint.savepoint import write_savepoint

        from flink_tpu.checkpoint.savepoint import check_savepoint_target

        def stop_sources():
            # pumps own the sources while running: stop them first, then
            # close (single-owner hand-back)
            for t, node in sources:
                if t.uid in active:
                    p = pumps.get(t.uid)
                    if p is not None:
                        p.stop()
                    t.source.close()
            active.clear()

        # serve at most the requests ALREADY QUEUED at this boundary:
        # under sustained lookup load, clients re-submit while a served
        # request's device read releases the GIL — an unbounded drain
        # would keep serving forever and starve the data path (observed
        # as a livelock in the serving smoke's batched mode)
        budget = max(control_queue.qsize(), 1)
        while budget > 0:
            budget -= 1
            try:
                req = control_queue.get_nowait()
            except _queue.Empty:
                return None
            if isinstance(req, (StateQueryRequest, StateQueryBatchRequest)):
                try:
                    req.finish(self._serve_query(graph, nodes, req))
                except BaseException as e:  # noqa: BLE001
                    req.finish(None, e)
                continue
            if isinstance(req, RescaleRequest):
                # the arbiter's per-job allocation: drain in-flight fires
                # (their buffers reference the pre-reshard plane), then
                # live-migrate — the same boundary checkpoints use
                try:
                    self._drain_pending(nodes, wait=True)
                    target = None
                    for node in nodes.values():
                        op = node.operator
                        if op is not None and getattr(
                                op, "supports_live_rescale", False):
                            target = op
                            break
                    if target is None:
                        raise RuntimeError(
                            f"job {job_name!r} has no live-rescalable "
                            "operator (mesh engine required)")
                    req.finish(target.reshard(req.new_shards))
                except BaseException as e:  # noqa: BLE001
                    req.finish(None, e)
                continue
            try:
                # fail fast on a bad target BEFORE any irreversible action
                # (closing sources / draining): a savepoint that cannot be
                # written must leave the job running (reference semantics)
                check_savepoint_target(req.path)
                if req.stop and req.drain:
                    # --drain: process the pumps' prefetched batches (their
                    # positions are already consumed-from-source), then
                    # flush every window/timer downstream before the
                    # snapshot so results are final (reference:
                    # stop-with-savepoint advanceToEndOfEventTime)
                    for t, node in sources:
                        if t.uid not in active:
                            continue
                        p = pumps.get(t.uid)
                        if p is not None:
                            p.stop_filling()
                            for batch, wm, pos in p.consume_remaining():
                                if pos is not None:
                                    source_positions[t.uid] = pos
                                if batch is _SourcePump._EOS:
                                    continue
                                self._emit_batch(node, batch)
                            if p.error is not None:
                                # a failed source must not masquerade as a
                                # clean end-of-stream in a FINAL savepoint
                                raise p.error
                        self._emit_watermark(node, MAX_WATERMARK)
                    stop_sources()
                self._drain_pending(nodes, wait=True)
                with traces.span("savepoint", req.path):
                    snap = self.snapshot_all(graph, nodes, source_positions,
                                             savepoint=True)
                    path = write_savepoint(req.path, job_name, snap,
                                           checkpoint_id=checkpoint_id)
                if req.stop and not req.drain:
                    stop_sources()
                req.finish(path)
            except BaseException as e:  # noqa: BLE001 - reported to caller
                req.finish(None, e)
                continue
            if req.stop:
                return req

    def _serve_query(self, graph, nodes, req):
        """Serve a single-key or batched state lookup. ALL reads route
        through the batched path: one gather program + ONE device read
        per request batch (a single key is a batch of one) — the old
        one-RTT-per-key loop is gone. Injected ``serving.lookup`` faults
        retry in place: lookups are read-only, so a retry cannot corrupt
        engine state (regression-pinned in tests/test_tenancy.py)."""
        keys = req.keys if isinstance(req, StateQueryBatchRequest) \
            else [req.key]
        for uid, node in nodes.items():
            t = node.transformation
            if req.operator_name in (t.name, graph.stable_id(t)):
                op = node.operator
                if op is None or not (hasattr(op, "query_state_batch")
                                      or hasattr(op, "query_state")):
                    raise RuntimeError(
                        f"operator {req.operator_name!r} has no queryable "
                        "state")

                def _lookup(op=op):
                    chaos.fault_point("serving.lookup",
                                      operator=req.operator_name,
                                      keys=len(keys),
                                      job=getattr(self, "_chaos_job",
                                                  None))
                    if hasattr(op, "query_state_batch"):
                        return op.query_state_batch(keys, req.namespace)
                    return [op.query_state(k, req.namespace)
                            for k in keys]

                out = chaos.run_recoverable("serving.lookup", _lookup)
                return out if isinstance(req, StateQueryBatchRequest) \
                    else out[0]
        raise KeyError(f"no operator named {req.operator_name!r}; "
                       f"available: "
                       f"{sorted(n.transformation.name for n in nodes.values())}")

    @staticmethod
    def _fail_pending_controls(control_queue, reason: str) -> None:
        """Complete any still-queued control requests so clients don't block
        on a job that already terminated."""
        if control_queue is None:
            return
        import queue as _queue

        while True:
            try:
                req = control_queue.get_nowait()
            except _queue.Empty:
                return
            req.finish(None, RuntimeError(reason))

    # --------------------------------------------- fire-deadline splitting

    def _deadline_observe(self, n: int, dt: float) -> None:
        """Fold one emitted chunk into the per-record rate EMA the
        splitter sizes chunks by."""
        if dt <= 1e-6 or n <= 0:
            return
        inst = n / dt
        self._deadline_rate = inst if self._deadline_rate <= 0 else (
            0.7 * self._deadline_rate + 0.3 * inst)

    def _emit_deadline_split(self, node: _Node, batch, nodes,
                             wm: Optional[int]) -> None:
        """Fire-deadline-aware micro-batching: split one source batch so
        each dispatch fits the latency.fire-deadline-ms budget at the
        MEASURED per-record step rate, advancing the watermark between
        splits and harvesting landed async fires — a due fire costs a
        bounded delta instead of waiting out a multi-hundred-ms batch.

        Intermediate watermarks are output-identical to the unsplit run:
        after chunk i the emitted watermark is
        ``min(final_wm, min timestamp of the REMAINING records - 1)``,
        so no remaining record of this batch can be late against it and
        no window fires before its last contributor arrived (the suffix
        minimum handles out-of-order timestamps within the batch)."""
        import numpy as np

        n = len(batch)
        rate = self._deadline_rate
        chunk = n if rate <= 0 else max(
            int(rate * self._fire_deadline_ms / 1000.0), 256)
        if chunk >= n:
            t0 = time.perf_counter()
            self._emit_batch(node, batch)
            self._deadline_observe(n, time.perf_counter() - t0)
            if wm is not None:
                self._emit_watermark(node, wm)
            return
        suffix_min = None
        if wm is not None and batch.has_timestamps:
            ts = np.asarray(batch.timestamps)
            suffix_min = np.minimum.accumulate(ts[::-1])[::-1]
        for a in range(0, n, chunk):
            b = min(a + chunk, n)
            t0 = time.perf_counter()
            self._emit_batch(node, batch.slice(a, b))
            self._deadline_observe(b - a, time.perf_counter() - t0)
            if b < n:
                if suffix_min is not None:
                    self._emit_watermark(
                        node, min(int(wm), int(suffix_min[b]) - 1))
                # harvest whatever landed; release held watermarks
                self._drain_pending(nodes)
        if wm is not None:
            self._emit_watermark(node, wm)

    # ------------------------------------------------------------- plumbing

    def _emit_batch(self, node: _Node, batch) -> None:
        """Route an output to children. Side outputs (TaggedBatch) go only to
        matching side-output edges; main outputs skip side-output edges
        (reference: OutputTag routing in OperatorChain)."""
        tag = batch.tag.name if isinstance(batch, TaggedBatch) else None
        payload = batch.batch if tag is not None else batch
        for child, idx in zip(node.children, node.child_input_idx):
            if child.transformation.side_tag == tag:
                self._process(child, payload, idx)

    def _emit_watermark(self, node: _Node, wm: int) -> None:
        for child, idx in zip(node.children, node.child_input_idx):
            self._process_watermark(child, wm, idx)

    def _process(self, node: _Node, batch: RecordBatch, input_idx: int) -> None:
        # chaos: a task crash mid-batch — surfaces through the normal
        # failure path (job fails, RestartStrategy decides, restore from
        # the latest checkpoint), exactly like a real UDF/executor death
        chaos.fault_point("task.batch", op=node.transformation.name,
                          job=getattr(self, "_chaos_job", None))
        node.records_in += len(batch)
        t0 = time.perf_counter()
        with flight.span("op.process"):
            outs = node.operator.process_batch(batch, input_idx)
        node.busy_s += time.perf_counter() - t0
        if node.marker_hist is not None:
            self._lat_plane.observe(node.marker_hist)
        for out in outs:
            self._forward(node, out)

    def _process_watermark(self, node: _Node, wm: int, input_idx: int) -> None:
        advanced = node.valve.advance(input_idx, wm)
        if advanced is None:
            return
        t0 = time.perf_counter()
        with flight.span("op.watermark", watermark=int(advanced)):
            outs = node.operator.process_watermark(advanced)
        node.busy_s += time.perf_counter() - t0
        for out in outs:
            self._forward(node, out)
        if node.operator.has_pending_output():
            # async fires in flight: the watermark must not overtake the
            # results it covers — hold it here; _drain_pending releases it
            # once the fires land (a later watermark simply supersedes)
            node.held_wm = advanced
            return
        node.held_wm = None
        self._emit_watermark(node, advanced)

    def _drain_pending(self, nodes: Dict[int, "_Node"],
                       wait: bool = False) -> None:
        """Forward any landed async-fire results; release held watermarks
        whose fires have all been emitted. With ``wait``, block until every
        pending output is drained (checkpoint / drain / close boundaries —
        a snapshot taken with undelivered results would lose them)."""
        while True:
            for node in nodes.values():
                op = node.operator
                if op is None:
                    continue
                if op.has_pending_output():
                    for out in op.poll_pending_output(wait=wait):
                        self._forward(node, out)
                if node.held_wm is not None and not op.has_pending_output():
                    wm = node.held_wm
                    node.held_wm = None
                    self._emit_watermark(node, wm)
            if not wait:
                return
            # a released watermark can cascade new fires in a downstream
            # window operator — iterate to the fixpoint before returning
            if not any(
                    n.operator is not None
                    and (n.operator.has_pending_output()
                         or n.held_wm is not None)
                    for n in nodes.values()):
                return

    def _forward(self, node: _Node, batch) -> None:
        n = len(batch.batch) if isinstance(batch, TaggedBatch) else len(batch)
        node.records_out += n
        # an INSTANT, not a span: _emit_batch recurses synchronously
        # into the whole downstream subtree, and a duration here would
        # multiply-count each level's op.process time in the per-kind
        # aggregates — the timeline marks WHEN each output left, the
        # durations belong to the operators
        flight.instant("emit")
        self._emit_batch(node, batch)

    # ----------------------------------------------------------- checkpoint

    @staticmethod
    def snapshot_all(graph: StreamGraph, nodes: Dict[int, _Node],
                     source_positions: Optional[Dict[int, Any]] = None,
                     delta: bool = False,
                     savepoint: bool = False) -> Dict[str, Any]:
        snap: Dict[str, Any] = {}
        for uid, node in nodes.items():
            t = node.transformation
            op = node.operator
            if op is None:
                # positions of the CONSUMED prefix, not the pump's
                # prefetched one — the checkpoint cut is the batch boundary
                if source_positions is not None and uid in source_positions:
                    state = {"source": source_positions[uid]}
                else:
                    state = {"source": t.source.snapshot_position()}
            elif delta and hasattr(op, "snapshot_state_delta"):
                state = op.snapshot_state_delta()
            elif savepoint and hasattr(op, "snapshot_state_savepoint"):
                # full, but preserving incremental dirty tracking — a
                # savepoint must not shrink the next delta checkpoint
                state = op.snapshot_state_savepoint()
            else:
                state = op.snapshot_state()
            if state:
                snap[graph.stable_id(t)] = state
        return snap

    @staticmethod
    def _restore_all(graph: StreamGraph, nodes: Dict[int, _Node],
                     states: Dict[str, Any]) -> None:
        consumed = set()
        for uid, node in nodes.items():
            t = node.transformation
            sid = graph.stable_id(t)
            state = states.get(sid)
            if state is None:
                continue
            consumed.add(sid)
            if node.operator is None:
                t.source.restore_position(state["source"])
            else:
                node.operator.restore_state(state)
        leftover = set(states) - consumed
        if leftover:
            # the reference fails on non-restored state by default
            # (allowNonRestoredState opt-in); silently dropping state here
            # would silently undercount aggregates after a graph edit
            raise RuntimeError(
                "checkpoint contains state for operators not present in the "
                f"graph (graph changed since snapshot?): {sorted(leftover)}")
