"""History server — finished jobs outlive the cluster.

reference: flink-runtime-web's HistoryServer: JobManagers archive
terminal jobs' REST payloads to a DFS directory
(`jobmanager.archive.fs.dir`); a standalone HistoryServer process serves
them after the cluster is gone.

Re-design: the JobMaster writes one JSON archive per terminal job
(status, attempts/state-machine transcript, metrics snapshot, checkpoint
trace spans) through the core.fs SPI (any scheme), and ``HistoryServer``
is a small standalone HTTP server over the archive directory."""

from __future__ import annotations

import json
import threading
from typing import Optional

from flink_tpu.core.config import ConfigOption


ARCHIVE_DIR = ConfigOption(
    "jobmanager.archive.dir", default=None, type=str,
    description="Directory (any core.fs scheme) where terminal jobs are "
    "archived for the history server. None = no archiving.")


_SUMMARY_FIELDS = ("job_id", "job_name", "status", "start_time",
                   "end_time", "attempts")


def _write_atomic(fs, local: str, payload: dict) -> None:
    data = json.dumps(payload, default=str).encode()
    tmp = local + ".tmp"
    with fs.open(tmp, "wb") as fh:
        fh.write(data)
    fs.rename(tmp, local)


def archive_job(archive_dir: str, job_id: str, payload: dict) -> str:
    """Write one terminal job's archive plus a small summary sidecar —
    the /jobs listing reads only sidecars, so listing latency does not
    scale with span/metric payload sizes (the reference's HistoryServer
    keeps a cached overview for the same reason)."""
    from flink_tpu.core.fs import get_filesystem

    fs, local = get_filesystem(archive_dir.rstrip("/") + f"/{job_id}.json")
    parent = local.rsplit("/", 1)[0]
    if parent and not fs.exists(parent):
        fs.mkdirs(parent)
    _write_atomic(fs, local, payload)
    _write_atomic(fs, local[:-5] + ".summary.json",
                  {k: payload.get(k) for k in _SUMMARY_FIELDS})
    return local


def read_archive(archive_dir: str, job_id: Optional[str] = None):
    from flink_tpu.core.fs import get_filesystem

    fs, local = get_filesystem(archive_dir)
    if job_id is not None:
        path = local.rstrip("/") + f"/{job_id}.json"
        if not fs.exists(path):
            return None
        with fs.open(path, "rb") as fh:
            return json.loads(fh.read())
    out = []
    if not fs.exists(local):
        return out
    for name in sorted(fs.listdir(local)):
        if not name.endswith(".summary.json"):
            continue
        with fs.open(local.rstrip("/") + f"/{name}", "rb") as fh:
            out.append(json.loads(fh.read()))
    return out


class HistoryServer:
    """Standalone REST surface over an archive directory (reference:
    HistoryServer): GET /jobs (summaries), GET /jobs/<id> (full archive).
    Runs without any cluster."""

    def __init__(self, archive_dir: str, port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.archive_dir = archive_dir
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                try:
                    parts = [p for p in
                             self.path.split("?")[0].split("/") if p]
                    if parts == ["jobs"] or not parts:
                        body = {"jobs": read_archive(outer.archive_dir)}
                    elif len(parts) == 2 and parts[0] == "jobs":
                        body = read_archive(outer.archive_dir, parts[1])
                        if body is None:
                            raise KeyError(parts[1])
                    else:
                        raise KeyError(self.path)
                    payload = json.dumps(body).encode()
                    self.send_response(200)
                except KeyError:
                    payload = json.dumps(
                        {"error": f"not found: {self.path}"}).encode()
                    self.send_response(404)
                except Exception as e:  # noqa: BLE001
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="history-server",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
