"""Restart backoff strategies.

reference: flink-runtime/.../executiongraph/failover/
FixedDelayRestartBackoffTimeStrategy.java,
ExponentialDelayRestartBackoffTimeStrategy.java,
FailureRateRestartBackoffTimeStrategy.java.
"""

from __future__ import annotations

import time
from typing import List


class RestartStrategy:
    def can_restart(self) -> bool:
        raise NotImplementedError

    def notify_failure(self) -> None:
        pass

    def backoff_ms(self) -> int:
        raise NotImplementedError


class NoRestartStrategy(RestartStrategy):
    def can_restart(self) -> bool:
        return False

    def backoff_ms(self) -> int:
        return 0


class FixedDelayRestartStrategy(RestartStrategy):
    def __init__(self, max_attempts: int = 3, delay_ms: int = 1000):
        self.max_attempts = max_attempts
        self.delay_ms = delay_ms
        self.attempts = 0

    def notify_failure(self) -> None:
        self.attempts += 1

    def can_restart(self) -> bool:
        return self.attempts < self.max_attempts

    def backoff_ms(self) -> int:
        return self.delay_ms


class ExponentialDelayRestartStrategy(RestartStrategy):
    def __init__(self, initial_ms: int = 100, max_ms: int = 60_000,
                 multiplier: float = 2.0, max_attempts: int = 10):
        self.initial_ms = initial_ms
        self.max_ms = max_ms
        self.multiplier = multiplier
        self.max_attempts = max_attempts
        self.attempts = 0
        self._current = initial_ms

    def notify_failure(self) -> None:
        if self.attempts > 0:
            self._current = min(self.max_ms,
                                int(self._current * self.multiplier))
        self.attempts += 1

    def can_restart(self) -> bool:
        return self.attempts < self.max_attempts

    def backoff_ms(self) -> int:
        return self._current


class FailureRateRestartStrategy(RestartStrategy):
    """Allow at most ``max_failures`` within ``interval_ms``."""

    def __init__(self, max_failures: int = 3, interval_ms: int = 60_000,
                 delay_ms: int = 1000):
        self.max_failures = max_failures
        self.interval_ms = interval_ms
        self.delay_ms = delay_ms
        self._failures: List[float] = []

    def notify_failure(self) -> None:
        now = time.monotonic() * 1000
        self._failures.append(now)
        cutoff = now - self.interval_ms
        self._failures = [t for t in self._failures if t >= cutoff]

    def can_restart(self) -> bool:
        return len(self._failures) < self.max_failures

    def backoff_ms(self) -> int:
        return self.delay_ms


def restart_strategy_from_config(config) -> RestartStrategy:
    from flink_tpu.core.config import RestartOptions

    kind = config.get(RestartOptions.STRATEGY)
    if kind == "none":
        return NoRestartStrategy()
    if kind == "fixed-delay":
        return FixedDelayRestartStrategy(
            config.get(RestartOptions.MAX_ATTEMPTS),
            config.get(RestartOptions.DELAY_MS))
    if kind == "exponential-delay":
        return ExponentialDelayRestartStrategy(
            initial_ms=config.get(RestartOptions.DELAY_MS),
            max_attempts=config.get(RestartOptions.MAX_ATTEMPTS))
    if kind == "failure-rate":
        return FailureRateRestartStrategy(
            max_failures=config.get(RestartOptions.MAX_ATTEMPTS),
            delay_ms=config.get(RestartOptions.DELAY_MS))
    raise ValueError(f"unknown restart strategy {kind!r}")
