"""Restart backoff strategies.

reference: flink-runtime/.../executiongraph/failover/
FixedDelayRestartBackoffTimeStrategy.java,
ExponentialDelayRestartBackoffTimeStrategy.java,
FailureRateRestartBackoffTimeStrategy.java.
"""

from __future__ import annotations

import random
import time
from typing import Callable, List, Optional


class RestartStrategy:
    def can_restart(self) -> bool:
        raise NotImplementedError

    def notify_failure(self) -> None:
        pass

    def backoff_ms(self) -> int:
        raise NotImplementedError


class NoRestartStrategy(RestartStrategy):
    def can_restart(self) -> bool:
        return False

    def backoff_ms(self) -> int:
        return 0


class FixedDelayRestartStrategy(RestartStrategy):
    def __init__(self, max_attempts: int = 3, delay_ms: int = 1000):
        self.max_attempts = max_attempts
        self.delay_ms = delay_ms
        self.attempts = 0

    def notify_failure(self) -> None:
        self.attempts += 1

    def can_restart(self) -> bool:
        return self.attempts < self.max_attempts

    def backoff_ms(self) -> int:
        return self.delay_ms


class ExponentialDelayRestartStrategy(RestartStrategy):
    """Exponential backoff with jitter and a quiet-period reset.

    reference: ExponentialDelayRestartBackoffTimeStrategy — after
    ``reset_backoff_threshold_ms`` of failure-free running the backoff
    (and attempt budget) resets to the initial values, so a job that
    recovered and ran healthily for a while is not punished with the
    max delay (or a spent budget) when it eventually fails again;
    ``jitter_factor`` spreads concurrent restarts by up to +/- that
    fraction of the current backoff (thundering-herd protection).

    ``seed`` pins the jitter PRNG (determinism for chaos runs);
    ``clock`` is injectable for tests (monotonic seconds).
    """

    def __init__(self, initial_ms: int = 100, max_ms: int = 60_000,
                 multiplier: float = 2.0, max_attempts: int = 10,
                 jitter_factor: float = 0.0,
                 reset_backoff_threshold_ms: int = 0,
                 seed: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.initial_ms = initial_ms
        self.max_ms = max_ms
        self.multiplier = multiplier
        self.max_attempts = max_attempts
        self.jitter_factor = float(jitter_factor)
        self.reset_backoff_threshold_ms = int(reset_backoff_threshold_ms)
        self.attempts = 0
        self._current = initial_ms
        self._rng = random.Random(seed)
        self._clock = clock
        self._last_failure_ms: Optional[float] = None

    def notify_failure(self) -> None:
        now_ms = self._clock() * 1000.0
        if (self.reset_backoff_threshold_ms > 0
                and self._last_failure_ms is not None
                and now_ms - self._last_failure_ms
                >= self.reset_backoff_threshold_ms):
            self._current = self.initial_ms
            self.attempts = 0
        if self.attempts > 0:
            self._current = min(self.max_ms,
                                int(self._current * self.multiplier))
        self.attempts += 1
        self._last_failure_ms = now_ms

    def can_restart(self) -> bool:
        return self.attempts < self.max_attempts

    def backoff_ms(self) -> int:
        if self.jitter_factor <= 0.0:
            return self._current
        spread = self._rng.uniform(-self.jitter_factor,
                                   self.jitter_factor)
        return max(0, int(self._current * (1.0 + spread)))


class FailureRateRestartStrategy(RestartStrategy):
    """Allow at most ``max_failures`` within ``interval_ms``."""

    def __init__(self, max_failures: int = 3, interval_ms: int = 60_000,
                 delay_ms: int = 1000,
                 clock: Callable[[], float] = time.monotonic):
        self.max_failures = max_failures
        self.interval_ms = interval_ms
        self.delay_ms = delay_ms
        self._clock = clock
        self._failures: List[float] = []

    def notify_failure(self) -> None:
        now = self._clock() * 1000
        self._failures.append(now)
        cutoff = now - self.interval_ms
        self._failures = [t for t in self._failures if t >= cutoff]

    def can_restart(self) -> bool:
        return len(self._failures) < self.max_failures

    def backoff_ms(self) -> int:
        return self.delay_ms


def restart_strategy_from_config(config) -> RestartStrategy:
    from flink_tpu.core.config import RestartOptions

    kind = config.get(RestartOptions.STRATEGY)
    if kind == "none":
        return NoRestartStrategy()
    if kind == "fixed-delay":
        return FixedDelayRestartStrategy(
            config.get(RestartOptions.MAX_ATTEMPTS),
            config.get(RestartOptions.DELAY_MS))
    if kind == "exponential-delay":
        return ExponentialDelayRestartStrategy(
            initial_ms=config.get(RestartOptions.DELAY_MS),
            max_ms=config.get(RestartOptions.MAX_BACKOFF_MS),
            multiplier=config.get(RestartOptions.BACKOFF_MULTIPLIER),
            max_attempts=config.get(RestartOptions.MAX_ATTEMPTS),
            jitter_factor=config.get(RestartOptions.JITTER_FACTOR),
            reset_backoff_threshold_ms=config.get(
                RestartOptions.RESET_BACKOFF_THRESHOLD_MS))
    if kind == "failure-rate":
        return FailureRateRestartStrategy(
            max_failures=config.get(RestartOptions.MAX_ATTEMPTS),
            interval_ms=config.get(
                RestartOptions.FAILURE_RATE_INTERVAL_MS),
            delay_ms=config.get(RestartOptions.DELAY_MS))
    raise ValueError(f"unknown restart strategy {kind!r}")
