"""Container deployment drivers — Kubernetes manifests + active scaling.

reference: flink-kubernetes (KubernetesResourceManagerDriver.java:1 —
the active RM requesting/releasing worker pods through the k8s API;
KubernetesClusterDescriptor deploying the JobManager Deployment +
Service + ConfigMap; taskmanager pod templates). The YARN driver plays
the same role on that stack; here Kubernetes is the container target.

TPU re-design: a TaskExecutor pod is a TPU-host pod — the worker spec
requests ``google.com/tpu`` device resources and pins the accelerator
type via the TPU nodeSelectors GKE uses, so "give me a worker" means
"give me chips". The control plane stays the standalone entrypoints
(``flink-tpu jobmanager`` / ``flink-tpu taskexecutor``): Kubernetes
only *schedules* them, exactly like the reference's native-k8s mode
runs the same entrypoints in pods.

Two layers:
- :class:`KubernetesDeployment` — renders the full manifest set and
  applies/scales/tears it down through a ``KubectlClient`` seam
  (subprocess ``kubectl`` in production; faked in tests — this
  environment has no cluster to talk to, so the seam IS the contract).
- :class:`ElasticScaler` — the ResourceManagerDriver role: watches
  unfulfilled slot demand and scales the TaskExecutor replica count,
  the reference's requestResource/releaseResource loop expressed as
  reconciliation (declarative replicas, like its
  KubernetesResourceManagerDriver requesting pods to match declared
  resources).
"""

from __future__ import annotations

import json
import shlex
import subprocess
from typing import Callable, Dict, List, Optional

from flink_tpu.core.config import Configuration


class KubectlClient:
    """Thin seam over ``kubectl`` (the k8s API client role). Everything
    the drivers need: apply JSON manifests, patch replicas, delete."""

    def __init__(self, context: Optional[str] = None,
                 namespace: str = "default"):
        self.context = context
        self.namespace = namespace

    def _base(self) -> List[str]:
        cmd = ["kubectl", "-n", self.namespace]
        if self.context:
            cmd += ["--context", self.context]
        return cmd

    def apply(self, manifest: dict) -> None:
        subprocess.run(self._base() + ["apply", "-f", "-"],
                       input=json.dumps(manifest).encode(), check=True)

    def scale(self, deployment: str, replicas: int) -> None:
        subprocess.run(self._base() + [
            "scale", "deployment", deployment,
            f"--replicas={int(replicas)}"], check=True)

    def delete(self, kind: str, name: str) -> None:
        subprocess.run(self._base() + [
            "delete", kind, name, "--ignore-not-found=true"], check=True)


def _config_args(config: Configuration) -> List[str]:
    return [f"-D{k}={v}" for k, v in sorted(config.to_dict().items())]


class KubernetesDeployment:
    """Render + drive the cluster's Kubernetes resources (reference:
    KubernetesClusterDescriptor.deploySessionCluster)."""

    def __init__(self, cluster_id: str, config: Optional[Configuration]
                 = None, image: str = "flink-tpu:latest",
                 task_executors: int = 2, slots_per_executor: int = 1,
                 tpus_per_executor: int = 0,
                 tpu_accelerator: str = "tpu-v5-lite-podslice",
                 tpu_topology: str = "1x1",
                 client: Optional[KubectlClient] = None):
        self.cluster_id = cluster_id
        self.config = config or Configuration({})
        self.image = image
        self.task_executors = int(task_executors)
        self.slots_per_executor = int(slots_per_executor)
        self.tpus_per_executor = int(tpus_per_executor)
        self.tpu_accelerator = tpu_accelerator
        self.tpu_topology = tpu_topology
        self.client = client or KubectlClient()

    # ------------------------------------------------------- manifests

    @property
    def jm_name(self) -> str:
        return f"{self.cluster_id}-jobmanager"

    @property
    def te_name(self) -> str:
        return f"{self.cluster_id}-taskexecutor"

    def _labels(self, component: str) -> Dict[str, str]:
        return {"app": "flink-tpu", "cluster": self.cluster_id,
                "component": component}

    def jobmanager_manifests(self) -> List[dict]:
        """JM Deployment (replicas=1) + Service exposing RPC + REST
        (reference: the JM Deployment/rest-service the descriptor
        creates)."""
        labels = self._labels("jobmanager")
        container = {
            "name": "jobmanager",
            "image": self.image,
            "args": ["flink-tpu", "jobmanager",
                     "--port", "6123", "--rest-port", "8081",
                     *_config_args(self.config)],
            "ports": [{"containerPort": 6123, "name": "rpc"},
                      {"containerPort": 8081, "name": "rest"}],
        }
        deployment = {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": self.jm_name, "labels": labels},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {"containers": [container]},
                },
            },
        }
        service = {
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": self.jm_name, "labels": labels},
            "spec": {
                "selector": labels,
                "ports": [
                    {"name": "rpc", "port": 6123, "targetPort": 6123},
                    {"name": "rest", "port": 8081, "targetPort": 8081},
                ],
            },
        }
        return [deployment, service]

    def taskexecutor_manifest(self) -> dict:
        """TE Deployment: each replica is one worker registering with the
        JM service; TPU workers request ``google.com/tpu`` devices and
        pin the slice type/topology via the GKE TPU nodeSelectors
        (reference: the worker pod template
        KubernetesResourceManagerDriver requests)."""
        labels = self._labels("taskexecutor")
        container: dict = {
            "name": "taskexecutor",
            "image": self.image,
            "args": ["flink-tpu", "taskexecutor",
                     "--jobmanager", f"{self.jm_name}:6123",
                     "--slots", str(self.slots_per_executor),
                     *_config_args(self.config)],
        }
        pod_spec: dict = {"containers": [container]}
        if self.tpus_per_executor:
            container["resources"] = {
                "requests": {"google.com/tpu": self.tpus_per_executor},
                "limits": {"google.com/tpu": self.tpus_per_executor},
            }
            pod_spec["nodeSelector"] = {
                "cloud.google.com/gke-tpu-accelerator":
                    self.tpu_accelerator,
                "cloud.google.com/gke-tpu-topology": self.tpu_topology,
            }
        return {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": self.te_name, "labels": labels},
            "spec": {
                "replicas": self.task_executors,
                "selector": {"matchLabels": labels},
                "template": {"metadata": {"labels": labels},
                             "spec": pod_spec},
            },
        }

    def manifests(self) -> List[dict]:
        return self.jobmanager_manifests() + [self.taskexecutor_manifest()]

    # --------------------------------------------------------- actions

    def deploy(self) -> None:
        for m in self.manifests():
            self.client.apply(m)

    def scale_task_executors(self, replicas: int) -> None:
        self.task_executors = int(replicas)
        self.client.scale(self.te_name, replicas)

    def teardown(self) -> None:
        self.client.delete("deployment", self.te_name)
        self.client.delete("deployment", self.jm_name)
        self.client.delete("service", self.jm_name)


class ElasticScaler:
    """The active ResourceManagerDriver role (reference:
    KubernetesResourceManagerDriver.requestResource): reconcile the
    worker replica count against observed slot demand.

    ``demand_fn`` returns (slots_required, slots_in_use) — e.g. pending
    slot requests and currently-allocated slots read over the RM's
    gateway. The scaler converts shortage or surplus into ONE
    declarative ``scale_task_executors`` call per reconcile, bounded by
    [min_workers, max_workers]. Scale-down never drops below the
    workers needed to hold the slots still IN USE — a bare
    ``kubectl scale`` kills arbitrary pods, so the floor is what keeps
    busy workers alive (the reference releases only idle-timed-out
    workers; declaratively that is the same floor)."""

    def __init__(self, deployment: KubernetesDeployment,
                 demand_fn: Callable[[], tuple],
                 slots_per_executor: Optional[int] = None,
                 min_workers: int = 1, max_workers: int = 64):
        self.deployment = deployment
        self.demand_fn = demand_fn
        self.slots_per = (slots_per_executor
                          or deployment.slots_per_executor or 1)
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)

    def reconcile(self) -> Optional[int]:
        """One reconcile step; returns the new replica count when a
        scale was issued, None when already converged."""
        required, in_use = self.demand_fn()

        def ceil_workers(slots: int) -> int:
            return -(-max(int(slots), 0) // self.slots_per)

        want = max(ceil_workers(required), ceil_workers(in_use))
        want = min(max(want, self.min_workers), self.max_workers)
        if want != self.deployment.task_executors:
            self.deployment.scale_task_executors(want)
            return want
        return None
