"""REST status endpoint for the MiniCluster.

reference: flink-runtime/.../rest (41k LoC of handlers) + the Angular web
dashboard. Scope here: the JSON monitoring surface the reference's dashboard
reads — cluster overview, job list, per-job status/metrics — served from a
background http.server thread.

GET  /ui, /ui/<asset>        the web dashboard (multi-view SPA,
                             flink_tpu/web/ — the flink-runtime-web role)
GET  /overview               cluster totals
GET  /jobs                   job summaries
GET  /jobs/<id>              one job's status
GET  /jobs/<id>/metrics      metric registry snapshot of the running attempt
GET  /jobs/<id>/state/<op>   queryable-state lookup (?key=K[&namespace=N])
GET  /jobs/<id>/flamegraph   sample the job's task threads (?duration_ms=N)
GET  /flamegraph             sample task threads cluster-wide (&all=1: every
                             thread incl. control plane)
GET  /taskexecutors          live executors + slots
POST /jobs/<id>/cancel       cancel the job
POST /jobs/<id>/savepoints   {"target": path, "stop": bool, "drain": bool}
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class RestServer:
    def __init__(self, cluster, port: int = 0):
        self.cluster = cluster
        rest = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                # "/" keeps serving the overview JSON (API compat);
                # the SPA lives under /ui
                clean = self.path.split("?")[0]
                if clean in ("/ui", "/index.html") \
                        or clean.startswith("/ui/"):
                    body, ctype = rest._static(clean)
                    if body is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                try:
                    payload = rest._route(self.path)
                except KeyError:
                    self.send_response(404)
                    self.end_headers()
                    return
                except Exception as e:  # noqa: BLE001
                    body = json.dumps({"error": repr(e)}).encode()
                    self.send_response(500)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                body = json.dumps(payload, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                try:
                    length = int(self.headers.get("Content-Length", 0) or 0)
                    body = json.loads(self.rfile.read(length) or b"{}") \
                        if length else {}
                    payload = rest._route_post(self.path, body)
                except KeyError:
                    self.send_response(404)
                    self.end_headers()
                    return
                except Exception as e:  # noqa: BLE001
                    out = json.dumps({"error": repr(e)}).encode()
                    self.send_response(500)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(out)))
                    self.end_headers()
                    self.wfile.write(out)
                    return
                out = json.dumps(payload, default=str).encode()
                self.send_response(202)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def log_message(self, *a):
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="rest-server", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- routing

    #: dashboard assets (flink_tpu/web — the flink-runtime-web
    #: web-dashboard role: a real multi-view SPA over this REST surface)
    _STATIC_TYPES = {".html": "text/html; charset=utf-8",
                     ".js": "application/javascript; charset=utf-8",
                     ".css": "text/css; charset=utf-8"}

    def _static(self, clean_path: str):
        import os

        name = clean_path[len("/ui/"):] if clean_path.startswith("/ui/") \
            else "index.html"
        if not name or "/" in name or name.startswith("."):
            return None, None
        web = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "web")
        path = os.path.join(web, name)
        ext = os.path.splitext(name)[1]
        if ext not in self._STATIC_TYPES or not os.path.isfile(path):
            return None, None
        with open(path, "rb") as f:
            return f.read(), self._STATIC_TYPES[ext]

    def _route(self, path: str):
        parts = [p for p in path.split("?")[0].split("/") if p]
        if parts == ["overview"] or not parts:
            return self._overview()
        if parts == ["jobs"]:
            return {"jobs": self.cluster.dispatcher.list_jobs()}
        if parts == ["taskexecutors"]:
            return self._executors()
        if len(parts) >= 2 and parts[0] == "jobs":
            job_id = parts[1]
            if len(parts) == 2:
                st = self.cluster.dispatcher.job_status(job_id)
                if st["status"] == "UNKNOWN":
                    raise KeyError(job_id)
                return dict(st, job_id=job_id)
            if parts[2] == "metrics":
                return self._job_metrics(job_id)
            if parts[2] == "plan":
                return {"job_id": job_id,
                        "plan": self.cluster.dispatcher.job_plan(job_id)}
            if parts[2] == "state" and len(parts) >= 4:
                return self._query_state(job_id, parts[3], path)
            if parts[2] == "flamegraph":
                if self.cluster.dispatcher.job_status(
                        job_id)["status"] == "UNKNOWN":
                    raise KeyError(job_id)
                return self._flamegraph(path, job_id=job_id)
        if parts == ["flamegraph"]:
            return self._flamegraph(path)
        raise KeyError(path)

    def _flamegraph(self, raw_path: str, job_id: str = None):
        """GET /flamegraph[?duration_ms=200&all=1] (cluster-wide task
        threads) and GET /jobs/<id>/flamegraph (that job's task threads —
        task threads are named task-<jobid>-<attempt>, so the job id IS
        the sampling filter). On-demand thread sampling folded into a
        frame tree (reference: VertexFlameGraph +
        JobVertexFlameGraphHandler)."""
        from urllib.parse import parse_qs, urlsplit

        from flink_tpu.metrics.flamegraph import (
            TASK_THREAD_PREFIXES,
            sample_flame_graph,
        )

        q = parse_qs(urlsplit(raw_path).query)
        duration = min(int(q.get("duration_ms", ["200"])[0]), 10_000)
        if job_id is not None:
            prefixes = [f"task-{job_id}"]
        elif q.get("all", ["0"])[0] == "1":
            prefixes = None
        else:
            prefixes = TASK_THREAD_PREFIXES
        return sample_flame_graph(duration_ms=duration,
                                  thread_name_prefixes=prefixes)

    def _query_state(self, job_id: str, operator_name: str, raw_path: str):
        """GET /jobs/<id>/state/<operator>?key=K[&namespace=N] — queryable
        state over REST (reference: queryable-state client, here on the
        monitoring port)."""
        from urllib.parse import parse_qs, unquote, urlsplit

        q = parse_qs(urlsplit(raw_path).query)
        if "key" not in q:
            raise KeyError("missing ?key=")
        key: object = q["key"][0]
        # key-type=string|int|float forces the key's Python type; the
        # default 'auto' tries int (string keys that LOOK numeric need the
        # explicit override — int 3 and "3" hash differently, like the
        # reference's typed key serializers)
        key_type = q.get("key-type", ["auto"])[0]
        if key_type == "int":
            key = int(key)
        elif key_type == "float":
            key = float(key)
        elif key_type == "auto":
            try:
                key = int(key)
            except ValueError:
                pass
        ns = int(q["namespace"][0]) if "namespace" in q else None
        result = self.cluster.dispatcher.query_state(
            job_id, unquote(operator_name), key, ns)
        return {"job_id": job_id, "operator": operator_name,
                "key": key, "state": result}

    def _route_post(self, path: str, body: dict):
        parts = [p for p in path.split("?")[0].split("/") if p]
        if len(parts) == 3 and parts[0] == "jobs" and parts[2] == "cancel":
            job_id = parts[1]
            if self.cluster.dispatcher.job_status(job_id)["status"] == \
                    "UNKNOWN":
                raise KeyError(job_id)
            self.cluster.dispatcher.cancel_job(job_id)
            return {"job_id": job_id, "status": "cancelling"}
        if len(parts) == 3 and parts[0] == "jobs" and \
                parts[2] == "savepoints":
            from flink_tpu.cluster.minicluster import JobClient

            target = body.get("target")
            if not target:
                raise ValueError("body must carry 'target'")
            client = JobClient(self.cluster, parts[1])
            if body.get("stop"):
                p = client.stop_with_savepoint(
                    target, drain=bool(body.get("drain")))
            else:
                p = client.trigger_savepoint(target)
            return {"job_id": parts[1], "savepoint": p}
        raise KeyError(path)

    def _overview(self):
        jobs = self.cluster.dispatcher.list_jobs()
        by_status: dict = {}
        for j in jobs:
            by_status[j["status"]] = by_status.get(j["status"], 0) + 1
        return {
            "taskexecutors": len(self.cluster.executors),
            "slots_total": sum(te.num_slots for te in self.cluster.executors),
            "jobs": by_status,
            "flink_tpu_version": _version(),
        }

    def _executors(self):
        # RM registry covers local AND remote (standalone) executors;
        # in-process ones add their live task view
        local = {te.endpoint_id: te.heartbeat()
                 for te in self.cluster.executors}
        out = []
        # through the RPC gateway: registry reads serialize on the RM main
        # thread instead of racing its mutations
        for eid, info in self.cluster.rm_gateway().executor_registry().items():
            entry = dict(local.get(eid, {"executor_id": eid}))
            entry.update(address=info["address"], slots=info["slots"],
                         allocated=info["allocated"],
                         heartbeat_age_s=round(info["heartbeat_age_s"], 3))
            out.append(entry)
        return {"executors": out}

    def _job_metrics(self, job_id: str):
        master = self.cluster.dispatcher.master(job_id)
        if master is None:
            raise KeyError(job_id)
        result = master.result
        if result is not None:
            snap = getattr(result, "metric_snapshot", None)
            if snap is None and getattr(result, "registry", None):
                snap = result.registry.snapshot()
            return {"job_id": job_id, "metrics": snap or {},
                    "spans": getattr(result, "spans", [])}
        return {"job_id": job_id, "metrics": {},
                "note": "job still running or no result yet"}

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _version() -> str:
    try:
        from flink_tpu.version import __version__

        return __version__
    except Exception:  # pragma: no cover
        return "unknown"
