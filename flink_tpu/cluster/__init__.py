from flink_tpu.cluster.local_executor import LocalExecutor

__all__ = ["LocalExecutor"]
