"""Location-transparent RPC backbone over gRPC.

reference: flink-rpc — RpcEndpoint/RpcGateway/RpcService
(flink-rpc-core/.../rpc/RpcEndpoint.java) implemented over Pekko actors with
JDK dynamic proxies (flink-rpc-akka/.../pekko/PekkoInvocationHandler.java,
PekkoRpcActor.java). Key semantics kept:

- every endpoint runs its handlers on ONE main thread (the reference's
  main-thread executor; MainThreadValidatorUtil assertions)
- gateways are dynamic proxies: attribute access returns a callable that
  marshals (endpoint, method, args) over the wire and blocks on the reply
- fencing tokens guard against split-brain leaders

Re-design: transport is gRPC's generic (un-protoc'ed) byte method with
cloudpickle payloads — one wire method, dynamic dispatch server-side, which
is exactly the shape of the reference's RockRpcInvocation messages.
"""

from __future__ import annotations

import atexit
import queue
import threading
import traceback
from concurrent import futures
from typing import Any, Dict, Optional

import cloudpickle
import grpc

_METHOD = "/flink_tpu.Rpc/Invoke"


class RpcException(RuntimeError):
    pass


class FencingTokenException(RpcException):
    pass


class RpcEndpoint:
    """Base class: subclass and define public methods; they become remotely
    callable. All calls execute serialized on this endpoint's main thread."""

    def __init__(self, endpoint_id: str):
        self.endpoint_id = endpoint_id
        self._mailbox: "queue.Queue" = queue.Queue()
        self._running = False
        self._main_thread: Optional[threading.Thread] = None
        self.fencing_token: Optional[int] = None

    # -- lifecycle ----------------------------------------------------------

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass

    def start(self) -> None:
        self._running = True
        self._main_thread = threading.Thread(
            target=self._main_loop, name=f"rpc-main-{self.endpoint_id}",
            daemon=True)
        self._main_thread.start()
        self.run_in_main_thread(self.on_start).result()

    def stop(self) -> None:
        if not self._running:
            return
        self.run_in_main_thread(self.on_stop).result()
        self._running = False
        self._mailbox.put(None)  # wake the loop
        self._main_thread.join(timeout=5)

    # -- main-thread executor ----------------------------------------------

    def _main_loop(self) -> None:
        while True:
            item = self._mailbox.get()
            if item is None:
                if not self._running:
                    return
                continue
            fn, args, kwargs, fut = item
            try:
                fut.set_result(fn(*args, **kwargs))
            except BaseException as e:  # noqa: BLE001 - marshalled to caller
                fut.set_exception(e)

    def run_in_main_thread(self, fn, *args, **kwargs) -> "futures.Future":
        fut: "futures.Future" = futures.Future()
        self._mailbox.put((fn, args, kwargs, fut))
        return fut

    def validate_main_thread(self) -> None:
        """reference: MainThreadValidatorUtil.isRunningInExpectedThread."""
        assert threading.current_thread() is self._main_thread, \
            "must run on the endpoint main thread"

    # -- dispatch (called by RpcService) ------------------------------------

    def _invoke(self, method: str, args, kwargs,
                fencing_token: Optional[int]) -> Any:
        if self.fencing_token is not None and \
                fencing_token != self.fencing_token:
            raise FencingTokenException(
                f"{self.endpoint_id}: fencing token mismatch "
                f"(got {fencing_token}, expected {self.fencing_token})")
        fn = getattr(self, method, None)
        if fn is None or method.startswith("_"):
            raise RpcException(
                f"no such rpc method {method!r} on {self.endpoint_id}")
        return self.run_in_main_thread(fn, *args, **kwargs).result()


class _GatewayProxy:
    """Dynamic proxy — the reference's PekkoInvocationHandler."""

    def __init__(self, invoke, endpoint_id: str,
                 fencing_token: Optional[int] = None):
        object.__setattr__(self, "_invoke_fn", invoke)
        object.__setattr__(self, "_endpoint_id", endpoint_id)
        object.__setattr__(self, "_fencing_token", fencing_token)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        def call(*args, **kwargs):
            return self._invoke_fn(self._endpoint_id, method, args, kwargs,
                                   self._fencing_token)

        return call

    def with_fencing_token(self, token: int) -> "_GatewayProxy":
        return _GatewayProxy(self._invoke_fn, self._endpoint_id, token)


#: process-wide client channels for server-less gateways
_client_channels: Dict[str, grpc.Channel] = {}
_client_lock = threading.Lock()

atexit.register(lambda: RpcService.client_close())

_CHANNEL_OPTIONS = [
    ("grpc.max_receive_message_length", 512 * 1024 * 1024),
    ("grpc.max_send_message_length", 512 * 1024 * 1024),
]


def _cached_channel(address: str, cache: Dict[str, grpc.Channel],
                    lock: threading.Lock) -> grpc.Channel:
    with lock:
        ch = cache.get(address)
        if ch is None:
            ch = grpc.insecure_channel(address, options=_CHANNEL_OPTIONS)
            cache[address] = ch
        return ch


def _make_gateway(channel: grpc.Channel, endpoint_id: str,
                  fencing_token: Optional[int],
                  call_timeout: float) -> "_GatewayProxy":
    stub = channel.unary_unary(
        _METHOD,
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b)

    def invoke(eid, method, args, kwargs, token):
        payload = cloudpickle.dumps((eid, method, args, kwargs, token))
        reply = cloudpickle.loads(stub(payload, timeout=call_timeout))
        if reply[0] == "ok":
            return reply[1]
        _, exc, tb = reply
        raise exc

    return _GatewayProxy(invoke, endpoint_id, fencing_token)


class RpcService:
    """Hosts endpoints on a gRPC server; connects gateways to remote ones."""

    def __init__(self, bind_address: str = "127.0.0.1", port: int = 0,
                 advertised_address: str = ""):
        self._endpoints: Dict[str, RpcEndpoint] = {}
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
            options=_CHANNEL_OPTIONS)
        handler = grpc.method_handlers_generic_handler(
            "flink_tpu.Rpc",
            {"Invoke": grpc.unary_unary_rpc_method_handler(
                self._serve,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b)})
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{bind_address}:{port}")
        self._server.start()
        # the address peers CONNECT to, which 0.0.0.0 never is: bind-all
        # servers advertise their routable host (reference:
        # taskmanager.host / jobmanager.rpc.address vs bind-host split)
        if not advertised_address:
            if bind_address == "0.0.0.0":
                import socket

                try:
                    advertised_address = socket.gethostbyname(
                        socket.gethostname())
                except OSError:
                    advertised_address = socket.gethostname()
            else:
                advertised_address = bind_address
        self.address = f"{advertised_address}:{self.port}"
        self._channels: Dict[str, grpc.Channel] = {}
        self._lock = threading.Lock()

    # -- server side --------------------------------------------------------

    def register(self, endpoint: RpcEndpoint) -> None:
        self._endpoints[endpoint.endpoint_id] = endpoint
        endpoint.start()

    def unregister(self, endpoint_id: str) -> None:
        ep = self._endpoints.pop(endpoint_id, None)
        if ep is not None:
            ep.stop()

    def _serve(self, request: bytes, context) -> bytes:
        try:
            endpoint_id, method, args, kwargs, token = \
                cloudpickle.loads(request)
            ep = self._endpoints.get(endpoint_id)
            if ep is None:
                raise RpcException(f"unknown endpoint {endpoint_id!r}")
            result = ep._invoke(method, args, kwargs, token)
            return cloudpickle.dumps(("ok", result))
        except BaseException as e:  # noqa: BLE001 - marshalled to caller
            return cloudpickle.dumps(
                ("err", e, traceback.format_exc()))

    # -- client side --------------------------------------------------------

    def _channel(self, address: str) -> grpc.Channel:
        return _cached_channel(address, self._channels, self._lock)

    def connect(self, address: str, endpoint_id: str,
                fencing_token: Optional[int] = None,
                call_timeout: float = 120) -> _GatewayProxy:
        """``call_timeout``: per-RPC deadline in seconds — liveness probes
        (heartbeats) use short deadlines so one unreachable peer cannot
        stall the caller for the default two minutes."""
        return _make_gateway(self._channel(address), endpoint_id,
                             fencing_token, call_timeout)

    def self_gateway(self, endpoint_id: str,
                     fencing_token: Optional[int] = None) -> _GatewayProxy:
        return self.connect(self.address, endpoint_id, fencing_token)

    @classmethod
    def client_connect(cls, address: str, endpoint_id: str,
                       fencing_token: Optional[int] = None,
                       call_timeout: float = 120) -> _GatewayProxy:
        """Client-only gateway: a channel to a remote endpoint without
        hosting a server (drivers submitting to a standalone cluster need
        no inbound RPC). Channels are cached process-wide; see
        :func:`client_close` for eviction."""
        ch = _cached_channel(address, _client_channels, _client_lock)
        return _make_gateway(ch, endpoint_id, fencing_token, call_timeout)

    @classmethod
    def client_close(cls, address: Optional[str] = None) -> None:
        """Close and evict cached client channels (one address, or all when
        ``address`` is None) — long-lived drivers rotating across many
        JobManagers would otherwise hold one channel per address for the
        process lifetime. Also runs at interpreter exit."""
        with _client_lock:
            targets = ([address] if address is not None
                       else list(_client_channels))
            for addr in targets:
                ch = _client_channels.pop(addr, None)
                if ch is not None:
                    try:
                        ch.close()
                    except Exception:  # noqa: BLE001 - best-effort cleanup
                        pass

    def stop(self) -> None:
        for ep in list(self._endpoints.values()):
            ep.stop()
        self._endpoints.clear()
        for ch in self._channels.values():
            ch.close()
        self._server.stop(grace=1)
