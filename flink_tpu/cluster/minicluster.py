"""MiniCluster — dispatcher / resource manager / task executors over real RPC.

reference: runtime/minicluster/MiniCluster.java runs Dispatcher + RM + N
TaskExecutors in one JVM with real RPC and real checkpoints (SURVEY.md §4
tier 3 — this is how the reference tests "multi-node" without a cluster);
Dispatcher.submitJob (runtime/dispatcher/Dispatcher.java:586), per-job
JobMaster (runtime/jobmaster/JobMaster.java:1263 startScheduling), slot
brokering (resourcemanager/ResourceManager.java), heartbeats
(runtime/heartbeat/HeartbeatManagerImpl.java), region failover + restart
backoff (executiongraph/failover/*).

Re-design: the same three roles as gRPC endpoints (flink_tpu.cluster.rpc) in
one process. A job's dataflow is one failover region (pipelined whole-graph
restart — the reference's behavior for fully-pipelined streaming jobs);
recovery restores the latest completed checkpoint. Job payloads travel
through the wire as cloudpickle, like the reference ships serialized
JobGraphs through Pekko.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional

from flink_tpu.cluster.local_executor import JobCancelledError, LocalExecutor
from flink_tpu.cluster.restart_strategies import (
    RestartStrategy,
    restart_strategy_from_config,
)
from flink_tpu.cluster.rpc import RpcEndpoint, RpcService
from flink_tpu.core.config import (
    CheckpointOptions,
    ClusterOptions,
    Configuration,
    DeploymentOptions,
    SchedulerOptions,
    StateOptions,
)

# job lifecycle (reference: org.apache.flink.api.common.JobStatus; the
# WAITING_FOR_RESOURCES state comes from the adaptive scheduler's state
# machine, reference: scheduler/adaptive/WaitingForResources.java)
CREATED = "CREATED"
WAITING_FOR_RESOURCES = "WAITING_FOR_RESOURCES"
RUNNING = "RUNNING"
RESTARTING = "RESTARTING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELED = "CANCELED"
TERMINAL = (FINISHED, FAILED, CANCELED)
_RESCALED = "RESCALED"  # internal attempt outcome, not a job status


class TaskExecutorEndpoint(RpcEndpoint):
    """Worker: owns task slots, runs deployed pipelines on task threads.

    reference: taskexecutor/TaskExecutor.java:659 submitTask -> Task thread
    -> StreamTask.invoke. Here a deployment is the whole (chained) pipeline,
    executed by the micro-batch task loop (LocalExecutor.run).
    """

    def __init__(self, executor_id: str, num_slots: int = 1,
                 master_timeout_s: Optional[float] = None):
        super().__init__(executor_id)
        self.num_slots = num_slots
        self._tasks: Dict[str, dict] = {}  # execution_id -> task record
        #: wall time of the last master contact (heartbeat ping); with
        #: ``master_timeout_s`` set, a watchdog cancels running tasks when
        #: the master goes silent — a partitioned worker must not keep
        #: writing checkpoints the failed-over attempt races (reference:
        #: TaskExecutor fails its tasks on heartbeat timeout to the JM)
        self._last_master_contact = time.monotonic()
        self._watchdog_stop = threading.Event()
        if master_timeout_s:
            def watchdog():
                while not self._watchdog_stop.wait(master_timeout_s / 4):
                    if time.monotonic() - self._last_master_contact \
                            > master_timeout_s:
                        self._cancel_all_tasks()

            threading.Thread(target=watchdog,
                             name=f"{executor_id}-master-watchdog",
                             daemon=True).start()

    def _cancel_all_tasks(self) -> None:
        for rec in list(self._tasks.values()):
            if rec["status"] == RUNNING:
                rec["cancel"].set()

    def on_stop(self) -> None:
        # a stopping worker takes its tasks down with it (reference:
        # TaskExecutor shutdown fails running tasks) — otherwise the task
        # threads keep running (and writing checkpoints) as zombies that
        # race the failed-over attempt
        self._watchdog_stop.set()
        self._cancel_all_tasks()

    # -- rpc: lifecycle -----------------------------------------------------

    #: terminal task records kept for status queries (bounded history)
    MAX_FINISHED_RECORDS = 32

    def _touch_master(self) -> None:
        self._last_master_contact = time.monotonic()

    def submit_task(self, execution_id: str, graph, config_dict: dict,
                    job_name: str, restore_from: Optional[str]) -> str:
        import queue

        # any master RPC proves the master is alive — a deployment from a
        # just-recovered master must not be killed by a stale watchdog
        # before the first heartbeat ping lands
        self._touch_master()
        cancel = threading.Event()
        control: "queue.Queue" = queue.Queue()
        record = {"status": RUNNING, "cancel": cancel, "result": None,
                  "error": None, "alive": True, "control": control}
        self._tasks[execution_id] = record
        self._prune_finished()

        def run():
            try:
                from flink_tpu.cluster.stage_executor import make_executor

                executor = make_executor(Configuration(config_dict), graph)
                result = executor.run(graph, job_name=job_name,
                                      restore_from=restore_from,
                                      cancel_event=cancel,
                                      control_queue=control)
                # store only the slim wire view: the live result's registry
                # gauges close over the whole operator DAG (device buffers,
                # native slot maps) and must not outlive the attempt
                record["result"] = _slim_result(result)
                record["status"] = FINISHED
            except JobCancelledError:
                record["status"] = CANCELED
            except BaseException as e:  # noqa: BLE001 - reported to master
                record["error"] = e
                record["status"] = FAILED
            finally:
                # a savepoint request racing with termination must not hang
                # its client: fail anything still queued or newly enqueued
                # between the executor's own drain and the status flip
                while True:
                    try:
                        req = control.get_nowait()
                    except queue.Empty:
                        break
                    req.finish(None, RuntimeError(
                        f"task {execution_id} already terminated"))

        t = threading.Thread(target=run, name=f"task-{execution_id}",
                             daemon=True)
        record["thread"] = t
        t.start()
        return execution_id

    def _prune_finished(self) -> None:
        terminal = [eid for eid, r in self._tasks.items()
                    if r["status"] in TERMINAL]
        excess = len(terminal) - self.MAX_FINISHED_RECORDS
        for eid in terminal[:max(0, excess)]:
            del self._tasks[eid]

    def cancel_task(self, execution_id: str) -> None:
        self._touch_master()
        rec = self._tasks.get(execution_id)
        if rec is not None:
            rec["cancel"].set()

    def trigger_savepoint(self, execution_id: str, path: str,
                          stop: bool = False, drain: bool = False) -> str:
        """Enqueue a savepoint (optionally stop-with-savepoint) for the
        task's next batch boundary; returns a request id to poll with
        ``savepoint_status`` (reference: TaskExecutor triggerCheckpoint RPC
        is async too — the ack arrives later). Non-blocking so the endpoint
        main thread stays responsive to heartbeats."""
        import uuid as _uuid

        from flink_tpu.cluster.local_executor import SavepointRequest

        self._touch_master()
        rec = self._tasks.get(execution_id)
        if rec is None or rec["status"] != RUNNING:
            raise RuntimeError(
                f"no running task {execution_id!r} to savepoint")
        req = SavepointRequest(path, stop=stop, drain=drain)
        request_id = _uuid.uuid4().hex[:12]
        rec.setdefault("savepoints", {})[request_id] = req
        rec["control"].put(req)
        return request_id

    def query_state(self, execution_id: str, operator_name: str, key,
                    namespace=None, timeout_s: float = 10.0):
        """Queryable-state lookup against a running task (reference:
        KvStateServer). Short blocking wait: queries are served at the very
        next batch boundary."""
        from flink_tpu.cluster.local_executor import StateQueryRequest

        self._touch_master()
        rec = self._tasks.get(execution_id)
        if rec is None or rec["status"] != RUNNING:
            raise RuntimeError(
                f"no running task {execution_id!r} to query")
        req = StateQueryRequest(operator_name, key, namespace)
        rec["control"].put(req)
        return req.wait(timeout_s)

    def query_state_batch(self, execution_id: str, operator_name: str,
                          keys, namespace=None, timeout_s: float = 10.0):
        """Batched lookup: the whole key list is served in one pass at
        the task's next batch boundary — one gather program + ONE device
        read (see LocalExecutor._serve_query)."""
        from flink_tpu.cluster.local_executor import StateQueryBatchRequest

        self._touch_master()
        rec = self._tasks.get(execution_id)
        if rec is None or rec["status"] != RUNNING:
            raise RuntimeError(
                f"no running task {execution_id!r} to query")
        req = StateQueryBatchRequest(operator_name, keys, namespace)
        rec["control"].put(req)
        return req.wait(timeout_s)

    def savepoint_status(self, execution_id: str, request_id: str) -> dict:
        self._touch_master()
        rec = self._tasks.get(execution_id)
        req = (rec or {}).get("savepoints", {}).get(request_id)
        if req is None:
            raise RuntimeError(f"unknown savepoint request {request_id!r}")
        if not req._done.is_set():
            return {"done": False}
        return {"done": True, "path": req.result_path, "error": req.error}

    def task_status(self, execution_id: str) -> dict:
        rec = self._tasks.get(execution_id)
        if rec is None:
            return {"status": "UNKNOWN", "error": None}
        return {"status": rec["status"], "error": rec["error"]}

    def task_result(self, execution_id: str):
        rec = self._tasks.get(execution_id)
        return None if rec is None else rec["result"]

    def running_count(self) -> int:
        """Slots currently occupied by running tasks (the registration
        slot report; also the heartbeat payload's `slots_free` input)."""
        return sum(1 for r in self._tasks.values()
                   if r["status"] == RUNNING)

    def heartbeat(self) -> dict:
        """reference: TaskExecutor heartbeat payload (slot report)."""
        self._last_master_contact = time.monotonic()
        return {"id": self.endpoint_id, "slots_total": self.num_slots,
                "slots_free": self.num_slots - self.running_count(),
                "ts": time.monotonic()}


class ResourceManagerEndpoint(RpcEndpoint):
    """Slot broker between JobMasters and TaskExecutors.

    reference: resourcemanager/ResourceManager.java (slot requests) +
    runtime/blocklist (bad nodes excluded from allocation).
    """

    def __init__(self):
        super().__init__("resourcemanager")
        self._executors: Dict[str, dict] = {}
        self._blocklist: set = set()
        #: eviction tombstones: eid -> last_heartbeat at eviction time. A
        #: re-registration inherits the stale liveness, so a one-way-
        #: partitioned worker (its keepalive reaches us, our pings don't
        #: reach it) cannot flap back to "fresh" every eviction; only an
        #: answered ping (heartbeat_from) clears the tombstone.
        self._evicted: Dict[str, float] = {}
        #: notification hook the hosting process sets to react to remote
        #: joins (adaptive-scheduler jobs rescale to new resources);
        #: invoked on the endpoint main thread — implementations must not
        #: block
        self.on_register = None

    def register_task_executor(self, executor_id: str, address: str,
                               num_slots: int,
                               running_tasks: int = 0) -> None:
        fresh = executor_id not in self._executors
        prev = self._executors.get(executor_id, {})
        # a keepalive RE-registration must NOT refresh liveness: a worker
        # that can reach the master while the master cannot reach it
        # (wrong advertised address, one-way partition) has to age out of
        # the registry — only answered pings (heartbeat_from) refresh.
        # An evicted worker's re-registration inherits its tombstoned
        # staleness so it cannot flap back in; a ping answer clears it.
        hb = prev.get("last_heartbeat",
                      self._evicted.get(executor_id, time.monotonic()))
        # After a JobManager restart the registry is empty, but a surviving
        # worker's tasks are still occupying slots. Seed a SEPARATE
        # `seeded` estimate from the worker's slot report on FRESH
        # registrations only (reference: TaskExecutor registration carries
        # a SlotReport) — it must not touch `allocated`, which is the
        # JobMaster-driven promise count, or a stale keepalive racing a
        # release would leak slots. `seeded` decays via heartbeat
        # reconciliation (heartbeat_from) as orphaned tasks finish.
        self._executors[executor_id] = {
            "address": address, "slots": num_slots,
            "allocated": prev.get("allocated", 0),
            "seeded": prev.get("seeded", running_tasks),
            "alloc_times": prev.get("alloc_times", []),
            "last_heartbeat": hb,
        }
        if fresh and self.on_register is not None:
            self.on_register(executor_id)

    def executor_registry(self) -> Dict[str, dict]:
        """Membership view: executor_id -> {address, slots, allocated,
        heartbeat_age_s} (REST /taskexecutors + the heartbeat pump)."""
        now = time.monotonic()
        return {
            eid: {"address": info["address"], "slots": info["slots"],
                  "allocated": info["allocated"] + info.get("seeded", 0),
                  "heartbeat_age_s": now - info["last_heartbeat"]}
            for eid, info in self._executors.items()
        }

    #: seconds a freshly promised slot may take to show up in the
    #: worker's running-task report; reconciliation credits promises
    #: younger than this instead of suspending entirely, so steady
    #: allocation churn cannot keep a stale orphan seed alive forever
    SEED_RECONCILE_GRACE_S = 10.0

    def heartbeat_from(self, executor_id: str,
                       running_tasks: Optional[int] = None) -> None:
        info = self._executors.get(executor_id)
        if info is not None:
            info["last_heartbeat"] = time.monotonic()
            if running_tasks is not None and info.get("seeded", 0):
                # reconcile the restart-seeded estimate against the live
                # slot report. Slots promised within the grace window may
                # not be RUNNING yet, so give the report the benefit of
                # exactly that many tasks — under steady churn the seed
                # still drains (orphans finishing can only shrink it),
                # instead of reconciliation being suspended whenever the
                # LAST allocation was recent.
                now = time.monotonic()
                recent = [t for t in info.get("alloc_times", [])
                          if now - t <= self.SEED_RECONCILE_GRACE_S]
                info["alloc_times"] = recent
                info["seeded"] = min(
                    info["seeded"],
                    max(0, running_tasks + len(recent)
                        - info["allocated"]))
        self._evicted.pop(executor_id, None)  # reachable again

    def mark_dead(self, executor_id: str) -> None:
        info = self._executors.pop(executor_id, None)
        if info is not None:
            self._evicted[executor_id] = info["last_heartbeat"]
            if len(self._evicted) > 256:  # bounded tombstone memory
                self._evicted.pop(next(iter(self._evicted)))

    def block_node(self, executor_id: str) -> None:
        self._blocklist.add(executor_id)

    def request_slot(self, exclude: tuple = ()) -> Optional[dict]:
        for eid, info in self._executors.items():
            if eid in self._blocklist or eid in exclude:
                continue
            if info["allocated"] + info.get("seeded", 0) < info["slots"]:
                info["allocated"] += 1
                now = time.monotonic()
                # pending-promise timestamps for seed reconciliation
                # (bounded: entries older than the grace window drop)
                info["alloc_times"] = [
                    t for t in info.get("alloc_times", [])
                    if now - t <= self.SEED_RECONCILE_GRACE_S] + [now]
                return {"executor_id": eid, "address": info["address"]}
        return None

    def release_slot(self, executor_id: str) -> None:
        info = self._executors.get(executor_id)
        if info is not None and info["allocated"] > 0:
            info["allocated"] -= 1

    def live_executors(self) -> List[str]:
        return list(self._executors)


def _slim_result(result) -> dict:
    """Wire-safe view of a JobExecutionResult: the live registry holds
    gauges closing over device state (not serializable, and shouldn't
    travel — the reference ships accumulator snapshots, not operators)."""
    return {
        "job_name": result.job_name,
        "metrics": result.metrics,
        "metric_snapshot":
            result.registry.snapshot() if result.registry else {},
        "spans": [
            {"scope": s.scope, "name": s.name,
             "duration_ms": s.duration_ms, "attributes": s.attributes}
            for s in (result.traces.spans() if result.traces else [])
        ],
    }


def _result_from_wire(wire: Optional[dict]):
    """Rebuild a client-side JobExecutionResult from the wire-safe dict."""
    if wire is None:
        return None
    from flink_tpu.datastream.environment import JobExecutionResult

    result = JobExecutionResult(wire["job_name"], wire["metrics"])
    result.metric_snapshot = wire.get("metric_snapshot", {})
    result.spans = wire.get("spans", [])
    return result


class JobMasterThread:
    """Per-job master: deploy, monitor, failover.

    reference: jobmaster/JobMaster.java + DefaultScheduler — here the
    scheduling problem is one failover region on one slot, so the master is
    a supervision loop: deploy -> watch heartbeats + task status -> on
    failure consult the RestartStrategy, restore from the latest checkpoint.
    """

    def __init__(self, cluster: "MiniCluster", job_id: str, job_name: str,
                 graph, config: Configuration):
        self.cluster = cluster
        self.job_id = job_id
        self.job_name = job_name
        self.graph = graph
        self.config = config
        self.status = CREATED
        self.attempt = 0
        self.error: Optional[BaseException] = None
        self.result = None
        self.restart_strategy: RestartStrategy = \
            restart_strategy_from_config(config)
        self.adaptive = config.get(SchedulerOptions.MODE) == "adaptive"
        #: adaptive-scheduler state machine transcript
        #: (reference: AdaptiveScheduler's State objects)
        self.state_history: List[tuple] = [(CREATED, time.time())]
        self._rescale_requested = threading.Event()
        self._cancel_requested = threading.Event()
        # suspension (cluster shutdown / leadership loss) terminates the
        # attempt but is NOT globally terminal: the job stays in the HA
        # store for the next leader (reference: JobStatus.SUSPENDED)
        self._suspended = threading.Event()
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"jobmaster-{job_id}", daemon=True)
        self._current_executor: Optional[str] = None
        self._current_address: Optional[str] = None
        self._current_execution_id: Optional[str] = None
        self._thread.start()

    # -- supervision loop ---------------------------------------------------

    def _run(self) -> None:
        # the supervision thread must always reach a terminal state and set
        # _done, or client.wait() blocks forever and the slot leaks
        try:
            self._supervise()
        except BaseException as e:  # noqa: BLE001 - job must terminate
            self.error = e
            self.status = FAILED
        finally:
            if self.status not in TERMINAL:
                self.status = FAILED
            self._archive()
            # globally-terminal jobs leave the HA job graph store; a
            # suspended job (cluster shutdown) stays for the next leader
            # (reference: Dispatcher#jobReachedTerminalState vs SUSPENDED)
            store = getattr(self.cluster, "job_graph_store", None)
            if store is not None and not self._suspended.is_set():
                try:
                    store.remove(self.job_id)
                except Exception:
                    pass
            self._done.set()

    def _set_status(self, status: str) -> None:
        self.status = status
        self.state_history.append((status, time.time()))

    def _archive(self) -> None:
        """Terminal jobs outlive the cluster: write the history-server
        archive (reference: JobManagers archive REST payloads to
        jobmanager.archive.fs.dir for the HistoryServer)."""
        from flink_tpu.cluster.history_server import ARCHIVE_DIR, archive_job

        if self._suspended.is_set():
            # a suspended job (cluster shutdown / leadership loss) is NOT
            # globally terminal — it stays in the HA store for the next
            # leader and must not appear archived (same guard as the
            # job-graph-store removal; reference:
            # Dispatcher#jobReachedTerminalState vs SUSPENDED)
            return
        # cluster-level setting with a per-job override (reference:
        # jobmanager.archive.fs.dir is a JobManager option)
        archive_dir = self.config.get(ARCHIVE_DIR) or \
            self.cluster.config.get(ARCHIVE_DIR)
        if not archive_dir:
            return
        try:
            payload = {
                "job_id": self.job_id,
                "job_name": self.job_name,
                "status": self.status,
                "attempts": self.attempt,
                "start_time": self.state_history[0][1],
                "end_time": time.time(),
                "state_history": [[s, t] for s, t in self.state_history],
                "error": repr(self.error) if self.error else None,
            }
            if self.result is not None:
                payload["metrics"] = getattr(self.result, "metrics", None)
                payload["metric_snapshot"] = getattr(
                    self.result, "metric_snapshot", None)
                traces = getattr(self.result, "spans", None)
                if traces is not None:
                    payload["spans"] = traces
            archive_job(archive_dir, self.job_id, payload)
        except Exception:  # noqa: BLE001 - archiving must not fail the job
            pass

    def _acquire_slot(self, rm):
        """Default mode: fail fast without a slot. Adaptive: enter
        WaitingForResources and poll until a slot appears or the wait
        timeout expires (reference: WaitingForResources state)."""
        slot = rm.request_slot()
        if slot is not None or not self.adaptive:
            return slot
        self._set_status(WAITING_FOR_RESOURCES)
        deadline = time.monotonic() + self.config.get(
            SchedulerOptions.RESOURCE_WAIT_TIMEOUT_MS) / 1000.0
        while time.monotonic() < deadline:
            if self._cancel_requested.is_set():
                return None
            slot = rm.request_slot()
            if slot is not None:
                # settle: let the resource picture stabilize briefly
                time.sleep(self.config.get(
                    SchedulerOptions.RESOURCE_STABILIZATION_MS) / 1000.0)
                return slot
            time.sleep(0.02)
        return None

    def _supervise(self) -> None:
        rm = self.cluster.rm_gateway()
        ckpt_dir = self.config.get(StateOptions.CHECKPOINT_DIR)
        while True:
            # re-read each attempt: request_rescale() retargets the
            # stage parallelism between attempts (the cold rescale path)
            want_stage_par = self.config.get(
                DeploymentOptions.STAGE_PARALLELISM)
            slot = self._acquire_slot(rm)
            if slot is None:
                if self._cancel_requested.is_set():
                    self._set_status(CANCELED)
                    return
                self._set_status(FAILED)
                self.error = RuntimeError(
                    "no slots available" + (
                        " within the resource wait timeout"
                        if self.adaptive else ""))
                return
            self._current_executor = slot["executor_id"]
            self._current_address = slot["address"]
            execution_id = f"{self.job_id}-{self.attempt}"
            self._current_execution_id = execution_id
            # slot demand = SUM over slot sharing groups of the group's
            # max parallelism (reference:
            # SlotSharingExecutionSlotAllocator): a group containing the
            # keyed stage needs stage-parallelism slots, any other group
            # needs one. Acquire what the cluster can actually give,
            # release any surplus immediately, and scale the stage to
            # the remainder — reactive, like the adaptive scheduler.
            extra_slots: List[dict] = []
            config = self.config
            per_group = max(want_stage_par, 1)
            keyed_count, plain_count = 1, 0
            if hasattr(self.graph, "slot_groups"):
                resolved = self.graph.slot_groups()
                keyed_groups = {resolved[t.uid]
                                for t in self.graph.nodes if t.keyed}
                all_groups = set(resolved.values()) or {"default"}
                keyed_count = len(keyed_groups)
                plain_count = len(all_groups) - keyed_count
            want_slots = per_group * keyed_count + plain_count
            if want_slots > 1:
                for _ in range(want_slots - 1):
                    extra = rm.request_slot()
                    if extra is None:
                        break
                    extra_slots.append(extra)
                total = 1 + len(extra_slots)
                effective = (max(1, min(per_group,
                                        (total - plain_count)
                                        // keyed_count))
                             if keyed_count else 1)
                used = effective * keyed_count + plain_count
                while len(extra_slots) + 1 > used:
                    # surplus from the floor division: give it back now
                    # (a held-but-unused slot starves other jobs AND
                    # joins the failover region for no benefit)
                    surplus = extra_slots.pop()
                    try:
                        rm.release_slot(surplus["executor_id"])
                    except Exception:
                        pass
                if want_stage_par > 1 and effective != want_stage_par:
                    config = Configuration(
                        {**self.config.to_dict(),
                         "execution.stage-parallelism": effective})
            participating = [slot["executor_id"]] + [
                s["executor_id"] for s in extra_slots]
            try:
                te = self.cluster.service.connect(slot["address"],
                                                  slot["executor_id"])
                restore = self._latest_restore_path(ckpt_dir)
                self._set_status(RUNNING)
                te.submit_task(execution_id, self.graph,
                               config.to_dict(), self.job_name, restore)
                outcome = self._watch(te, execution_id,
                                      participating=participating)
                if outcome == FINISHED:
                    self.result = _result_from_wire(
                        te.task_result(execution_id))
            except Exception as e:  # executor vanished mid-deploy
                self.error = e
                outcome = FAILED
            finally:
                for s in [slot] + extra_slots:
                    try:
                        rm.release_slot(s["executor_id"])
                    except Exception:
                        pass
            if outcome == FINISHED:
                self._set_status(FINISHED)
                return
            if outcome == CANCELED:
                self._set_status(CANCELED)
                return
            if outcome == _RESCALED:
                if self._cancel_requested.is_set():
                    self._set_status(CANCELED)
                    return
                # reactive rescale (adaptive scheduler): redeploy from the
                # latest checkpoint on the changed resource set WITHOUT
                # consuming restart budget — a rescale is not a failure
                # (reference: AdaptiveScheduler Executing -> Restarting on
                # resource change)
                self._rescale_requested.clear()
                self.attempt += 1
                self._set_status(RESTARTING)
                continue
            # failure path
            self.restart_strategy.notify_failure()
            if self._cancel_requested.is_set():
                self._set_status(CANCELED)
                return
            if not self.restart_strategy.can_restart():
                self._set_status(FAILED)
                return
            self.attempt += 1
            self._set_status(RESTARTING)
            time.sleep(self.restart_strategy.backoff_ms() / 1000.0)

    def _watch(self, te, execution_id: str,
               participating: Optional[List[str]] = None) -> str:
        """Poll task status + executor liveness until a terminal outcome.

        ``participating`` lists every executor holding one of this job's
        slots (subtask expansion spans executors); losing ANY of them fails
        the attempt — the whole pipeline is one failover region."""
        timeout_s = self.config.get(
            ClusterOptions.HEARTBEAT_TIMEOUT_MS) / 1000.0
        rescaling = False
        watch_executors = participating or [self._current_executor]
        while True:
            if self._cancel_requested.is_set():
                try:
                    te.cancel_task(execution_id)
                except Exception:
                    return CANCELED
            elif self._rescale_requested.is_set() and not rescaling:
                # adaptive reactive rescale: stop this attempt cleanly; the
                # supervision loop redeploys on the new resource picture
                rescaling = True
                try:
                    te.cancel_task(execution_id)
                except Exception:
                    return _RESCALED
            try:
                st = te.task_status(execution_id)
            except Exception as e:  # executor gone: treat as task failure
                self.error = RuntimeError(
                    f"task executor lost: {e}")
                if self._current_executor:
                    self.cluster.rm_gateway().mark_dead(
                        self._current_executor)
                return FAILED
            if st["status"] in TERMINAL:
                if rescaling and st["status"] == CANCELED and \
                        not self._cancel_requested.is_set():
                    # user cancellation racing the rescale wins: never
                    # resurrect a cancelled job
                    return _RESCALED
                self.error = st["error"]
                return st["status"]
            for eid in watch_executors:
                hb = self.cluster.last_heartbeat(eid)
                # a missing record means the executor left the membership
                # entirely (killed/unregistered) — every registration seeds
                # a timestamp, so None is as dead as a timed-out beat
                if hb is None or time.monotonic() - hb > timeout_s:
                    self.error = RuntimeError(
                        f"heartbeat timeout for {eid}")
                    self.cluster.rm_gateway().mark_dead(eid)
                    try:
                        te.cancel_task(execution_id)
                    except Exception:
                        pass
                    return FAILED
            time.sleep(0.01)

    @staticmethod
    def _latest_restore_path(ckpt_dir: Optional[str]) -> Optional[str]:
        if not ckpt_dir:
            return None
        from flink_tpu.checkpoint.storage import CheckpointStorage

        try:
            store = CheckpointStorage(ckpt_dir)
            if store.latest_checkpoint_id() is not None:
                return ckpt_dir
        except FileNotFoundError:
            pass
        return None

    def on_new_resources(self) -> None:
        """Reactive-mode hook: the resource picture changed (reference:
        AdaptiveScheduler#onNewResourcesAvailable). A rescale redeploy is
        only safe when the job can resume from a checkpoint — without
        checkpointing it would replay from record 0 and double-emit (the
        reference's reactive mode likewise requires checkpointing)."""
        if not (self.adaptive and self.status == RUNNING):
            return
        if self._can_rescale():
            self._rescale_requested.set()

    def _can_rescale(self) -> bool:
        """A rescale redeploy replays from the latest checkpoint; without
        checkpointing it would replay from record 0 and double-emit."""
        return bool(self.config.get(StateOptions.CHECKPOINT_DIR)) and bool(
            self.config.get(CheckpointOptions.INTERVAL_MS)
            or self.config.get(CheckpointOptions.EVERY_N_BATCHES))

    def request_rescale(self, parallelism: int) -> bool:
        """Autoscaler entry point — the COLD rescale path: retarget the
        keyed stage parallelism and redeploy from the latest checkpoint
        (key-group-range filtered restore re-shards the state; no
        restart budget is consumed — a rescale is not a failure).
        Returns False when the job cannot rescale right now (not
        running, or no checkpointing to resume from); the mesh engines'
        LIVE path (engine.reshard) never stops the job at all.

        reference: AdaptiveScheduler Executing -> Restarting on a
        resource-requirements change (the externally-driven form of
        on_new_resources)."""
        parallelism = int(parallelism)
        if parallelism < 1:
            raise ValueError(f"parallelism must be >= 1: {parallelism}")
        if self.status != RUNNING or not self._can_rescale():
            return False
        if parallelism == self.config.get(
                DeploymentOptions.STAGE_PARALLELISM):
            return False
        self.config = Configuration({
            **self.config.to_dict(),
            DeploymentOptions.STAGE_PARALLELISM.key: parallelism})
        self._rescale_requested.set()
        return True

    @property
    def current_parallelism(self) -> int:
        """The stage parallelism the current/next attempt deploys with
        (the autoscale controller's current_shards view)."""
        return int(self.config.get(DeploymentOptions.STAGE_PARALLELISM))

    # -- client surface -----------------------------------------------------

    def cancel(self) -> None:
        self._cancel_requested.set()

    def suspend(self) -> None:
        """Terminate the attempt WITHOUT removing the job from the HA
        store (cluster shutdown / leadership loss)."""
        self._suspended.set()
        self._cancel_requested.set()

    def trigger_savepoint(self, path: str, stop: bool = False,
                          drain: bool = False) -> dict:
        """Start a savepoint of the running attempt; returns polling
        coordinates (reference: JobMaster triggerSavepoint returns a
        CompletableFuture — here the client polls savepoint_status)."""
        if self.status != RUNNING or self._current_executor is None:
            raise RuntimeError(
                f"job {self.job_id} is {self.status}, cannot savepoint")
        te = self.cluster.service.connect(self._current_address,
                                          self._current_executor)
        request_id = te.trigger_savepoint(
            self._current_execution_id, path, stop, drain)
        return {"executor_id": self._current_executor,
                "address": self._current_address,
                "execution_id": self._current_execution_id,
                "request_id": request_id}

    def query_state(self, operator_name: str, key, namespace=None):
        if self.status != RUNNING or self._current_executor is None:
            raise RuntimeError(
                f"job {self.job_id} is {self.status}, cannot query state")
        te = self.cluster.service.connect(self._current_address,
                                          self._current_executor)
        return te.query_state(self._current_execution_id, operator_name,
                              key, namespace)

    def query_state_batch(self, operator_name: str, keys, namespace=None):
        if self.status != RUNNING or self._current_executor is None:
            raise RuntimeError(
                f"job {self.job_id} is {self.status}, cannot query state")
        te = self.cluster.service.connect(self._current_address,
                                          self._current_executor)
        return te.query_state_batch(self._current_execution_id,
                                    operator_name, keys, namespace)

    def wait(self, timeout: Optional[float] = None) -> str:
        self._done.wait(timeout)
        return self.status


class DispatcherEndpoint(RpcEndpoint):
    """Job submission front door; spawns a JobMaster per job.

    reference: dispatcher/Dispatcher.java:586 submitJob.
    """

    def __init__(self, cluster: "MiniCluster"):
        super().__init__("dispatcher")
        self.cluster = cluster
        self._masters: Dict[str, JobMasterThread] = {}
        self._recovery_lock = threading.Lock()

    def submit_job(self, graph, config_dict: dict, job_name: str,
                   job_id: Optional[str] = None) -> str:
        job_id = job_id or uuid.uuid4().hex[:16]
        store = getattr(self.cluster, "job_graph_store", None)
        if store is not None:
            # persist BEFORE starting: a dispatcher that dies right after
            # accepting the submission must still recover the job
            store.put(job_id, job_name, graph, config_dict)
        master = JobMasterThread(self.cluster, job_id, job_name, graph,
                                 Configuration(config_dict))
        self._masters[job_id] = master
        return job_id

    def recover_jobs(self, leader_check=None) -> List[str]:
        """Resubmit every unfinished job from the HA job graph store
        (reference: Dispatcher HA recovery via JobGraphStore on leadership
        grant). ``leader_check`` is re-consulted before each resubmission —
        recovery may run concurrently with a leadership loss."""
        store = getattr(self.cluster, "job_graph_store", None)
        if store is None:
            return []
        # leadership can flap: two grants -> two recovery threads; the lock
        # serializes them so the check-then-insert on _masters cannot race
        # and double-start a job
        with self._recovery_lock:
            return self._recover_jobs_locked(store, leader_check)

    def _recover_jobs_locked(self, store, leader_check) -> List[str]:
        recovered = []
        for job_id in store.job_ids():
            if leader_check is not None and not leader_check():
                return recovered  # leadership lost mid-recovery: stop
            existing = self._masters.get(job_id)
            if existing is not None:
                if existing._suspended.is_set():
                    # a master this dispatcher suspended on leadership loss
                    # is resumed when leadership returns (transient renew
                    # blip) — once its thread has wound down
                    if not existing._done.wait(timeout=10):
                        continue  # still winding down; next grant retries
                elif existing.status in TERMINAL:
                    # a terminal (FINISHED/FAILED/CANCELED) job still in
                    # the store means its remove() silently failed — retry
                    # the removal, NEVER re-run it (duplicate sink output)
                    try:
                        store.remove(job_id)
                    except Exception:
                        pass
                    continue
                else:
                    continue  # live master: must not double-start
            rec = store.get(job_id)
            master = JobMasterThread(self.cluster, job_id, rec["job_name"],
                                     rec["graph"],
                                     Configuration(rec["config"]))
            self._masters[job_id] = master
            recovered.append(job_id)
        return recovered

    def job_plan(self, job_id: str) -> dict:
        """The chained JobGraph of a submitted job (reference: REST
        /jobs/:id/plan served from JsonPlanGenerator output)."""
        m = self._masters.get(job_id)
        if m is None:
            raise KeyError(job_id)
        from flink_tpu.core.config import CoreOptions
        from flink_tpu.graph.job_graph import build_job_graph

        return build_job_graph(
            m.graph,
            default_parallelism=m.config.get(
                CoreOptions.DEFAULT_PARALLELISM)).to_json()

    def job_status(self, job_id: str) -> dict:
        m = self._masters.get(job_id)
        if m is None:
            return {"status": "UNKNOWN"}
        return {"status": m.status, "attempt": m.attempt,
                "error": repr(m.error) if m.error else None,
                "name": m.job_name,
                "state_history": [
                    {"state": s, "ts": ts} for s, ts in m.state_history]}

    def list_jobs(self) -> List[dict]:
        return [dict(self.job_status(jid), job_id=jid)
                for jid in self._masters]

    def cancel_job(self, job_id: str) -> None:
        m = self._masters.get(job_id)
        if m is not None:
            m.cancel()

    def trigger_savepoint(self, job_id: str, path: str, stop: bool = False,
                          drain: bool = False) -> dict:
        m = self._masters.get(job_id)
        if m is None:
            raise RuntimeError(f"unknown job {job_id}")
        return m.trigger_savepoint(path, stop=stop, drain=drain)

    def query_state(self, job_id: str, operator_name: str, key,
                    namespace=None):
        m = self._masters.get(job_id)
        if m is None:
            raise RuntimeError(f"unknown job {job_id}")
        return m.query_state(operator_name, key, namespace)

    def query_state_batch(self, job_id: str, operator_name: str, keys,
                          namespace=None):
        m = self._masters.get(job_id)
        if m is None:
            raise RuntimeError(f"unknown job {job_id}")
        return m.query_state_batch(operator_name, keys, namespace)

    # local-only helpers (not serializable across processes)
    def master(self, job_id: str) -> Optional[JobMasterThread]:
        return self._masters.get(job_id)


class JobClient:
    """Handle on a submitted job (reference: core/execution/JobClient)."""

    def __init__(self, cluster: "MiniCluster", job_id: str):
        self.cluster = cluster
        self.job_id = job_id

    def status(self) -> dict:
        return self.cluster.dispatcher.job_status(self.job_id)

    def cancel(self) -> None:
        self.cluster.dispatcher.cancel_job(self.job_id)

    def trigger_savepoint(self, path: str, timeout_s: float = 60.0) -> str:
        """reference: JobClient.triggerSavepoint."""
        return self._savepoint(path, stop=False, drain=False,
                               timeout_s=timeout_s)

    def stop_with_savepoint(self, path: str, drain: bool = False,
                            timeout_s: float = 60.0) -> str:
        """reference: JobClient.stopWithSavepoint (--drain flushes all
        windows/timers before the snapshot)."""
        return self._savepoint(path, stop=True, drain=drain,
                               timeout_s=timeout_s)

    def _savepoint(self, path: str, stop: bool, drain: bool,
                   timeout_s: float) -> str:
        coords = self.cluster.dispatcher_gateway().trigger_savepoint(
            self.job_id, path, stop=stop, drain=drain)
        te = self.cluster.service.connect(coords["address"],
                                          coords["executor_id"])
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            st = te.savepoint_status(coords["execution_id"],
                                     coords["request_id"])
            if st["done"]:
                if st["error"] is not None:
                    raise st["error"]
                return st["path"]
            time.sleep(0.02)
        raise TimeoutError(f"savepoint {path!r} did not complete in "
                           f"{timeout_s}s")

    def wait(self, timeout: Optional[float] = None) -> dict:
        master = self.cluster.dispatcher.master(self.job_id)
        if master is not None:
            master.wait(timeout)
        return self.status()

    def result(self):
        master = self.cluster.dispatcher.master(self.job_id)
        return master.result if master else None


class MiniCluster:
    """RM + Dispatcher control plane with real gRPC between the roles and a
    background heartbeat pump. With ``cluster.task-executors`` > 0 it hosts
    that many TaskExecutors in-process (the reference MiniCluster); with 0
    it is a standalone JobManager — pin ``rpc.port`` and join remote
    TaskExecutor processes via flink_tpu.cluster.standalone
    (reference: StandaloneSessionClusterEntrypoint + TaskManagerRunner)."""

    def __init__(self, config: Optional[Configuration] = None):
        from flink_tpu.core.config import HighAvailabilityOptions

        self.config = config or Configuration()
        self.service = RpcService(
            bind_address=self.config.get(ClusterOptions.RPC_BIND_ADDRESS),
            port=self.config.get(ClusterOptions.RPC_PORT),
            advertised_address=self.config.get(
                ClusterOptions.RPC_ADVERTISED_ADDRESS))
        self.rm = ResourceManagerEndpoint()
        self.service.register(self.rm)
        # HA services (reference: HighAvailabilityServices wiring)
        self.job_graph_store = None
        self.blob_store = None
        ha_mode = self.config.get(HighAvailabilityOptions.MODE)
        ha_dir = self.config.get(HighAvailabilityOptions.STORAGE_DIR)
        if ha_mode == "filesystem" and ha_dir:
            from flink_tpu.cluster.ha import BlobStore, JobGraphStore

            self.job_graph_store = JobGraphStore(ha_dir)
            self.blob_store = BlobStore(ha_dir)
        self.dispatcher = DispatcherEndpoint(self)
        self.service.register(self.dispatcher)
        self.executors: List[TaskExecutorEndpoint] = []
        self._heartbeats: Dict[str, float] = {}
        self._hb_stop = threading.Event()
        n = self.config.get(ClusterOptions.NUM_TASK_EXECUTORS)
        slots = self.config.get(ClusterOptions.SLOTS_PER_EXECUTOR)
        for i in range(n):
            self.add_task_executor(slots)
        # HA recovery happens only on winning dispatcher leadership — a
        # standby sharing the storageDir must NOT also run the jobs
        # (reference: DispatcherLeaderProcess recovers on leadership grant)
        self._leader_election = None
        if self.job_graph_store is not None:
            from flink_tpu.cluster.ha import (
                FileLeaderElectionDriver,
                LeaderContender,
                LeaderElectionService,
            )
            from flink_tpu.core.config import HighAvailabilityOptions

            cluster = self

            class _DispatcherContender(LeaderContender):
                def grant_leadership(self, fencing_token):
                    # recovery can block on winding-down masters, so it runs
                    # OFF the election thread (which must keep renewing the
                    # lease) and re-checks leadership before each resubmit
                    election = cluster._leader_election

                    def _recover():
                        cluster.dispatcher.recover_jobs(
                            leader_check=lambda: election is None
                            or election.is_leader)

                    threading.Thread(target=_recover,
                                     name="dispatcher-recovery",
                                     daemon=True).start()

                def revoke_leadership(self):
                    # split-brain guard: the new leader's recover_jobs()
                    # will resubmit these jobs from the JobGraphStore, so
                    # this dispatcher must stop running them (suspend keeps
                    # them in the HA store for the new leader)
                    for master in list(
                            cluster.dispatcher._masters.values()):
                        try:
                            master.suspend()
                        except Exception:
                            pass

            lease_s = self.config.get(
                HighAvailabilityOptions.LEASE_TIMEOUT_MS) / 1000.0
            self._leader_election = LeaderElectionService(
                FileLeaderElectionDriver(
                    self.config.get(HighAvailabilityOptions.STORAGE_DIR),
                    "dispatcher", lease_timeout_s=lease_s),
                _DispatcherContender(), poll_interval_s=min(lease_s / 4,
                                                            0.25))
            self._leader_election.start()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="heartbeat-manager",
            daemon=True)
        self._hb_thread.start()
        self._rest = None
        rest_port = self.config.get(ClusterOptions.REST_PORT)
        if rest_port >= 0:
            from flink_tpu.cluster.rest import RestServer

            self._rest = RestServer(self, port=rest_port)

        # remote TE joins must wake adaptive-scheduler jobs, exactly like
        # add_task_executor does for local ones. Wired LAST: the RM is
        # network-reachable the moment its endpoint registers, and a
        # keepalive re-registration from a surviving worker must not hit a
        # callback touching attributes that don't exist yet. (Joins that
        # land before this line just miss the wake-up; the keepalive
        # re-register and the heartbeat pump pick them up.)
        cluster_ref = self

        def _on_remote_register(executor_id: str) -> None:
            self._heartbeats[executor_id] = time.monotonic()

            def wake():
                for master in list(
                        cluster_ref.dispatcher._masters.values()):
                    master.on_new_resources()

            threading.Thread(target=wake, name="resource-wake",
                             daemon=True).start()

        self.rm.on_register = _on_remote_register

    # -- membership ---------------------------------------------------------

    def add_task_executor(self, num_slots: int = 1) -> TaskExecutorEndpoint:
        te = TaskExecutorEndpoint(f"taskexecutor-{len(self.executors)}",
                                  num_slots)
        self.service.register(te)
        self.rm_gateway().register_task_executor(
            te.endpoint_id, self.service.address, num_slots)
        self.executors.append(te)
        self._heartbeats[te.endpoint_id] = time.monotonic()
        # adaptive-scheduler jobs react to the changed resource picture
        for master in list(self.dispatcher._masters.values()):
            master.on_new_resources()
        return te

    def kill_task_executor(self, executor_id: str) -> None:
        """Fault injection: make an executor vanish (tests; the reference
        kills TaskManagers in its recovery ITCases — SURVEY.md §4)."""
        for te in list(self.executors):
            if te.endpoint_id == executor_id:
                for rec in te._tasks.values():
                    rec["cancel"].set()
                self.service.unregister(executor_id)
                # drop from membership so REST /taskexecutors and /overview
                # stop reporting the dead executor's slots as capacity
                self.executors.remove(te)
        self._heartbeats.pop(executor_id, None)
        self.rm_gateway().mark_dead(executor_id)

    # -- heartbeats ---------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        from concurrent import futures as _futures

        interval = self.config.get(
            ClusterOptions.HEARTBEAT_INTERVAL_MS) / 1000.0
        timeout_s = self.config.get(
            ClusterOptions.HEARTBEAT_TIMEOUT_MS) / 1000.0
        rm = self.rm_gateway()  # through RPC: keep the main-thread invariant
        # parallel pings with a short per-RPC deadline: one blackholed
        # remote worker must not starve every healthy executor's refresh
        # (serial pings with the default 120s deadline would)
        pool = _futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="hb-ping")
        ping_deadline = max(min(timeout_s / 2, 5.0), 0.5)

        def ping(eid: str, address: str) -> bool:
            gw = self.service.connect(address, eid,
                                      call_timeout=ping_deadline)
            report = gw.heartbeat()
            self._heartbeats[eid] = time.monotonic()
            # forward the slot report so the RM reconciles its
            # restart-seeded occupancy estimate against live truth
            running = (report["slots_total"] - report["slots_free"]
                       if isinstance(report, dict)
                       and "slots_free" in report else None)
            rm.heartbeat_from(eid, running_tasks=running)
            return True

        try:
            while not self._hb_stop.wait(interval):
                # every registered executor, local AND remote — each
                # pinged at its own registered address (reference:
                # HeartbeatManager pings TaskManagers wherever they run)
                try:
                    registry = rm.executor_registry()
                except Exception:
                    continue
                fs = {pool.submit(ping, eid, info["address"]): eid
                      for eid, info in registry.items()}
                answered = set()
                try:
                    for f in _futures.as_completed(
                            fs, timeout=max(timeout_s, ping_deadline) + 1):
                        try:
                            if f.result():
                                answered.add(fs[f])
                        except Exception:
                            pass  # missed beat; timeout decides
                except _futures.TimeoutError:
                    pass  # stragglers keep running into their deadline
                # evict executors silent for several timeouts so their
                # slots stop being offered and their pings stop costing.
                # Liveness is re-read AFTER this round's pings: an
                # executor that just answered (e.g. after the pump itself
                # was suspended for a while) must never be evicted on a
                # stale pre-ping snapshot.
                try:
                    registry = rm.executor_registry()
                except Exception:
                    continue
                for eid, info in registry.items():
                    if eid not in answered \
                            and info["heartbeat_age_s"] > timeout_s * 3:
                        try:
                            rm.mark_dead(eid)
                        except Exception:
                            pass
        finally:
            pool.shutdown(wait=False)

    def last_heartbeat(self, executor_id: str) -> Optional[float]:
        return self._heartbeats.get(executor_id)

    # -- gateways -----------------------------------------------------------

    def rm_gateway(self):
        return self.service.connect(self.service.address, "resourcemanager")

    def dispatcher_gateway(self):
        return self.service.connect(self.service.address, "dispatcher")

    # -- client surface -----------------------------------------------------

    def submit(self, env, job_name: str = "job") -> JobClient:
        """Submit a built StreamExecutionEnvironment pipeline."""
        graph = env.get_stream_graph()
        env._sinks = []
        job_id = self.dispatcher_gateway().submit_job(
            graph, env.config.to_dict(), job_name)
        return JobClient(self, job_id)

    @property
    def rest_port(self) -> Optional[int]:
        return self._rest.port if self._rest else None

    def shutdown(self) -> None:
        if self._leader_election is not None:
            self._leader_election.stop()  # graceful release -> standby wins
        self._hb_stop.set()
        for jid, master in list(self.dispatcher._masters.items()):
            if self.job_graph_store is not None:
                master.suspend()  # job survives in the HA store
            else:
                master.cancel()
        if self._rest is not None:
            self._rest.close()
        self.service.stop()
