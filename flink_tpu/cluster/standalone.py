"""Standalone deployment: JobManager and TaskExecutor as separate
processes joined over gRPC.

reference: StandaloneSessionClusterEntrypoint (the jobmanager.sh process:
Dispatcher + ResourceManager + REST) and TaskManagerRunner (the
taskmanager.sh process registering with the ResourceManager and offering
slots). The control plane here is the same MiniCluster code — a
MiniCluster with ``cluster.task-executors: 0`` IS the standalone
JobManager; this module adds the worker-side runner and the process
entrypoints (exposed as ``flink-tpu jobmanager`` / ``flink-tpu
taskexecutor``).

The data plane between stage-parallel subtasks picks its transport via
``shuffle.service`` (gRPC for cross-process); checkpoints/savepoints need
a filesystem path all processes share (``state.checkpoints.dir``), like
the reference's requirement of a shared checkpoint directory.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from flink_tpu.core.config import ClusterOptions, Configuration
from flink_tpu.cluster.minicluster import TaskExecutorEndpoint
from flink_tpu.cluster.rpc import RpcService


class TaskExecutorRunner:
    """One worker process: hosts a TaskExecutorEndpoint on its own gRPC
    server, registers with the remote ResourceManager, and keeps
    re-registering as a liveness keepalive (a restarted JobManager
    re-learns the worker without manual intervention; re-registration
    preserves slot accounting server-side)."""

    def __init__(self, jobmanager_address: str,
                 config: Optional[Configuration] = None,
                 executor_id: Optional[str] = None):
        self.config = config or Configuration()
        self.jm_address = jobmanager_address
        self.service = RpcService(
            bind_address=self.config.get(ClusterOptions.RPC_BIND_ADDRESS),
            advertised_address=self.config.get(
                ClusterOptions.RPC_ADVERTISED_ADDRESS))
        self.executor_id = executor_id or f"taskexecutor-{uuid.uuid4().hex[:8]}"
        self.num_slots = self.config.get(ClusterOptions.SLOTS_PER_EXECUTOR)
        # a worker that loses its master cancels its tasks rather than
        # keep writing output/checkpoints the failover will race
        timeout_s = self.config.get(
            ClusterOptions.HEARTBEAT_TIMEOUT_MS) / 1000.0
        self.endpoint = TaskExecutorEndpoint(self.executor_id,
                                             self.num_slots,
                                             master_timeout_s=timeout_s * 3)
        self.service.register(self.endpoint)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return self.service.address

    def register_once(self) -> None:
        rm = self.service.connect(self.jm_address, "resourcemanager")
        # running_count iterates the endpoint's task dict — read it on the
        # endpoint main thread (keepalive runs on its own thread; a
        # concurrent submit_task would otherwise mutate mid-iteration)
        running = self.endpoint.run_in_main_thread(
            self.endpoint.running_count).result()
        rm.register_task_executor(self.executor_id, self.service.address,
                                  self.num_slots, running_tasks=running)

    def start(self) -> "TaskExecutorRunner":
        self.register_once()
        interval = self.config.get(
            ClusterOptions.HEARTBEAT_INTERVAL_MS) / 1000.0

        def keepalive():
            while not self._stop.wait(max(interval * 4, 1.0)):
                try:
                    self.register_once()
                except Exception:
                    pass  # JobManager away; keep trying (it may restart)

        self._thread = threading.Thread(target=keepalive,
                                        name="te-keepalive", daemon=True)
        self._thread.start()
        return self

    def run_forever(self) -> None:
        self.start()
        try:
            while not self._stop.wait(3600):
                pass
        except KeyboardInterrupt:
            pass
        finally:
            self.stop()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # an in-flight keepalive re-register completing AFTER
            # mark_dead would resurrect a dead entry in the RM registry
            self._thread.join(timeout=10)
        try:
            rm = self.service.connect(self.jm_address, "resourcemanager",
                                      call_timeout=5)
            rm.mark_dead(self.executor_id)
        except Exception:
            pass
        self.service.stop()


def run_jobmanager(config: Optional[Configuration] = None):
    """Start the standalone JobManager (blocking). Equivalent of
    ``MiniCluster`` with no local executors + a pinned rpc.port."""
    from flink_tpu.cluster.minicluster import MiniCluster

    config = config or Configuration()
    config.set("cluster.task-executors", 0)
    cluster = MiniCluster(config)
    print(f"jobmanager rpc on {cluster.service.address}"
          + (f", rest on :{cluster.rest_port}"
             if cluster.rest_port else ""), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        cluster.shutdown()


def remote_submit(jobmanager_address: str, env, job_name: str = "job"):
    """Submit a built pipeline to a remote standalone JobManager; returns
    (job_id, dispatcher_gateway) — poll with ``job_status(job_id)``.
    Client-only: no server is hosted, channels are cached process-wide."""
    dispatcher = RpcService.client_connect(jobmanager_address, "dispatcher")
    graph = env.get_stream_graph()
    env._sinks = []
    # effective config: includes CLI -D dynamic properties and restore
    # flags, exactly what a local execute() would apply
    config = env._effective_config() if hasattr(
        env, "_effective_config") else env.config
    job_id = dispatcher.submit_job(graph, config.to_dict(), job_name)
    return job_id, dispatcher
