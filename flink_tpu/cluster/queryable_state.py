"""Queryable state client: external point-lookup of live keyed state.

reference: flink-queryable-state (QueryableStateClient in
flink-queryable-state-client-java querying the TM-side KvStateServer over
Netty). Re-design: lookups route through the existing gRPC control plane to
the owning task, and are served ON the task loop at a batch boundary — so
they read a consistent cut without the reference's concurrent-access
caveats, at the cost of up to one micro-batch of latency.

Serving-path contract (the tenancy rework): EVERY read — single key or
batch — travels as a :class:`StateQueryBatchRequest` and is served by one
gather program + ONE ``jax.device_get`` for the whole batch; the old
one-RTT-per-key path is gone. On top, concurrent ``get_state`` callers
from different threads COALESCE into shared device batches
(:class:`~flink_tpu.tenancy.serving.LookupCoalescer`), so a high-QPS
serving workload pays one device round trip per request batch, not per
lookup.

Usage::

    client = QueryableStateClient(cluster)
    result = client.get_state(job_id, "window_agg(SumAggregate)", key=7)
    # -> {namespace -> {output column -> value}}
    results = client.get_state_batch(job_id, "window_agg(SumAggregate)",
                                     keys=[7, 8, 9])
    # -> one result dict per key, request order
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class QueryableStateClient:
    #: default ride-collection window 0: flush immediately — the drain
    #: loop still coalesces whatever concurrent callers queued, but a
    #: SEQUENTIAL caller (always the lone flusher) pays no wait at all;
    #: a nonzero window only helps sustained multi-thread load, where
    #: the ServingPlane (which keeps one) is the intended surface.
    def __init__(self, cluster, coalesce_window_ms: float = 0.0,
                 max_batch: int = 512):
        from flink_tpu.tenancy.serving import CoalescerPool

        self.cluster = cluster

        def make_flush(key):
            # the RAW rpc: the coalescer's _drain already records the
            # batch against its counters — routing through
            # get_state_batch here would double-count coalesced lookups
            def flush(keys, namespace, _j=key[0], _o=key[1]):
                return self._query_batch_rpc(_j, _o, keys, namespace)

            return flush

        #: the shared coalescer lifecycle (creation race, retirement
        #: accounting, stats shape) — one behavior with ServingPlane
        self._pool = CoalescerPool(make_flush, max_batch=int(max_batch),
                                   window_ms=float(coalesce_window_ms))

    # ------------------------------------------------------------------ API

    def _serving_plane(self):
        """The cluster's ServingPlane when it exposes one (the tenancy
        session cluster): lookups then take the native fast path — the
        whole key batch probes the GIL-free hot-row table in ONE call
        before any Python-per-key work, and only misses ride the
        replica worker queues. RPC-gateway clusters return None and
        keep the control-plane route."""
        return getattr(self.cluster, "serving", None)

    def get_state(self, job_id: str, operator_name: str, key,
                  namespace: Optional[int] = None
                  ) -> Dict[int, Dict[str, Any]]:
        """Finished result columns for ``key`` in the named stateful
        operator; one entry per live namespace (window), or just the one
        requested. Thin wrapper over the batched path: the lookup rides
        whatever device batch concurrent callers are forming."""
        plane = self._serving_plane()
        if plane is not None:
            t0 = time.perf_counter()
            out = plane.lookup(job_id, operator_name, key, namespace)
            # keep client-side stats counting point lookups on the
            # plane route (the legacy coalescer path counted each one)
            self._coalescer(job_id, operator_name).note_batch(
                1, (time.perf_counter() - t0) * 1e3)
            return out
        return self._coalescer(job_id, operator_name).lookup(
            key, namespace)

    def get_state_batch(self, job_id: str, operator_name: str, keys,
                        namespace: Optional[int] = None
                        ) -> List[Dict[int, Dict[str, Any]]]:
        """One result dict per key, request order — a single RPC (or
        one batched serving-plane probe, see :meth:`_serving_plane`)
        and a single device batch for the whole list. Recorded against
        the (job, operator) coalescer's counters (as ServingPlane's
        ``lookup_batch``) so :meth:`stats` covers the explicit-batch
        shape too, not just coalesced ``get_state`` traffic."""
        t0 = time.perf_counter()
        plane = self._serving_plane()
        if plane is not None:
            out = plane.lookup_batch(job_id, operator_name, keys,
                                     namespace)
        else:
            out = self._query_batch_rpc(job_id, operator_name, keys,
                                        namespace)
        self._coalescer(job_id, operator_name).note_batch(
            len(out), (time.perf_counter() - t0) * 1e3)
        return out

    def get_state_batch_packed(self, job_id: str, operator_name: str,
                               keys):
        """The zero-copy batch form: against a serving-plane cluster,
        hit results stay in the native probe's packed buffers and
        materialize per key only on read (bit-identical to
        :meth:`get_state_batch` when consumed). Against an RPC-gateway
        cluster it wraps the ordinary batch — same read surface either
        way."""
        from flink_tpu.tenancy.serving import PackedLookupResult

        t0 = time.perf_counter()
        plane = self._serving_plane()
        if plane is not None:
            out = plane.lookup_batch_packed(job_id, operator_name,
                                            keys)
        else:
            out = PackedLookupResult.from_dicts(self._query_batch_rpc(
                job_id, operator_name, keys, None))
        self._coalescer(job_id, operator_name).note_batch(
            len(out), (time.perf_counter() - t0) * 1e3)
        return out

    def _query_batch_rpc(self, job_id: str, operator_name: str, keys,
                         namespace: Optional[int] = None):
        return self.cluster.dispatcher_gateway().query_state_batch(
            job_id, operator_name, list(keys), namespace)

    # ------------------------------------------------------------ coalescing

    def _coalescer(self, job_id: str, operator_name: str):
        return self._pool.get((job_id, operator_name))

    def forget_job(self, job_id: str) -> None:
        """Drop the job's coalescers — a long-lived client querying
        many short-lived jobs grows one coalescer (and its latency
        reservoir) per (job, operator) forever otherwise. Counters
        fold into retained totals so :meth:`stats` stays cumulative —
        including a lookup racing the forget (retired coalescers
        redirect late counts into the pool). Querying the job again
        AFTER forgetting re-creates its tracking (by design — the job
        may still be running); forget again when done."""
        self._pool.retire(lambda k: k[0] == job_id)

    def stats(self) -> Dict[str, float]:
        """Client-side amortization evidence: lookups vs device batches
        and the p99 end-to-end lookup latency (retained totals from
        forgotten jobs included)."""
        return self._pool.stats()
