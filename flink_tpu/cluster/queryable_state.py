"""Queryable state client: external point-lookup of live keyed state.

reference: flink-queryable-state (QueryableStateClient in
flink-queryable-state-client-java querying the TM-side KvStateServer over
Netty). Re-design: lookups route through the existing gRPC control plane to
the owning task, and are served ON the task loop at a batch boundary — so
they read a consistent cut without the reference's concurrent-access
caveats, at the cost of up to one micro-batch of latency.

Usage::

    client = QueryableStateClient(cluster)
    result = client.get_state(job_id, "window_agg(SumAggregate)", key=7)
    # -> {namespace -> {output column -> value}}
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class QueryableStateClient:
    def __init__(self, cluster):
        self.cluster = cluster

    def get_state(self, job_id: str, operator_name: str, key,
                  namespace: Optional[int] = None
                  ) -> Dict[int, Dict[str, Any]]:
        """Finished result columns for ``key`` in the named stateful
        operator; one entry per live namespace (window), or just the one
        requested."""
        return self.cluster.dispatcher_gateway().query_state(
            job_id, operator_name, key, namespace)
