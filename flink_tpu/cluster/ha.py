"""High availability: leader election, job graph store, blob store.

reference:
- leader election: runtime/leaderelection/DefaultLeaderElectionService.java
  with ZooKeeper (ZooKeeperLeaderElectionDriver) / Kubernetes ConfigMap
  drivers. Re-design: the same service/driver/contender split with a
  filesystem lease driver (atomic O_EXCL lock file + mtime-renewed lease,
  stale-lease takeover) — the coordination primitive available in this
  environment; ZK/K8s drivers would plug in through the same Driver SPI.
- fencing: each acquired leadership gets a fresh fencing token (the
  reference's leader session id) that RPCs carry.
- job graph store: runtime/jobmanager/JobGraphStore — submitted jobs are
  persisted so a failed-over dispatcher can recover them.
- blob store: runtime/blob/BlobServer — content-addressed artifact
  distribution with local caching.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import cloudpickle


# ---------------------------------------------------------------------------
# Leader election
# ---------------------------------------------------------------------------


class LeaderContender:
    """Callbacks the service invokes (reference: LeaderContender)."""

    def grant_leadership(self, fencing_token: int) -> None:
        raise NotImplementedError

    def revoke_leadership(self) -> None:
        raise NotImplementedError


class FileLeaderElectionDriver:
    """Filesystem lease: whoever atomically creates ``<dir>/<name>.lock``
    holds leadership; the holder renews the lease by touching the file; a
    lease not renewed within ``lease_timeout`` is stale and may be taken
    over (reference: the ZK ephemeral-node / K8s lease semantics)."""

    def __init__(self, storage_dir: str, name: str,
                 lease_timeout_s: float = 3.0):
        self.dir = storage_dir
        self.name = name
        self.lease_timeout_s = lease_timeout_s
        self.owner_id = uuid.uuid4().hex
        os.makedirs(storage_dir, exist_ok=True)

    @property
    def _lock_path(self) -> str:
        return os.path.join(self.dir, f"{self.name}.lock")

    def try_acquire(self) -> bool:
        path = self._lock_path
        payload = json.dumps({"owner": self.owner_id,
                              "ts": time.time()}).encode()
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, payload)
            os.close(fd)
            return True
        except FileExistsError:
            pass
        # stale-lease takeover
        try:
            with open(path) as f:
                data = json.load(f)
            if data.get("owner") == self.owner_id:
                return True
            age = time.time() - os.path.getmtime(path)
            if age > self.lease_timeout_s:
                # steal via atomic replace so two stealers cannot both win
                tmp = path + f".steal-{self.owner_id}"
                with open(tmp, "w") as f:
                    f.write(payload.decode())
                os.replace(tmp, path)
                time.sleep(0.01)  # let a racing replace land
                with open(path) as f:
                    return json.load(f).get("owner") == self.owner_id
        except (OSError, ValueError):
            pass
        return False

    def renew(self) -> bool:
        """Touch the lease; False if leadership was lost.

        The touch races a stale-lease ``os.replace`` steal (try_acquire):
        between our read and the utime a stealer may have replaced the
        file, so verify ownership AFTER touching — a renewing loser must
        observe the loss rather than both sides believing they lead."""
        path = self._lock_path
        try:
            with open(path) as f:
                if json.load(f).get("owner") != self.owner_id:
                    return False
            os.utime(path, None)
            with open(path) as f:
                return json.load(f).get("owner") == self.owner_id
        except (OSError, ValueError):
            return False

    def release(self) -> None:
        try:
            with open(self._lock_path) as f:
                if json.load(f).get("owner") == self.owner_id:
                    os.remove(self._lock_path)
        except (OSError, ValueError):
            pass


class LeaderElectionService:
    """Drives a contender through grant/revoke using a driver
    (reference: DefaultLeaderElectionService)."""

    def __init__(self, driver: FileLeaderElectionDriver,
                 contender: LeaderContender,
                 poll_interval_s: float = 0.1):
        self.driver = driver
        self.contender = contender
        self.poll_interval_s = poll_interval_s
        self.is_leader = False
        self.fencing_token: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name=f"leader-election-{self.driver.name}",
            daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self.is_leader:
                if self.driver.try_acquire():
                    self.is_leader = True
                    self.fencing_token = uuid.uuid4().int & ((1 << 62) - 1)
                    try:
                        self.contender.grant_leadership(self.fencing_token)
                    except Exception:
                        pass
            else:
                if not self.driver.renew():
                    self.is_leader = False
                    self.fencing_token = None
                    try:
                        self.contender.revoke_leadership()
                    except Exception:
                        pass
            self._stop.wait(self.poll_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        if self.is_leader:
            self.driver.release()
            self.is_leader = False


# ---------------------------------------------------------------------------
# Job graph store
# ---------------------------------------------------------------------------


class JobGraphStore:
    """Persist submitted jobs for dispatcher failover recovery
    (reference: runtime/jobmanager/DefaultJobGraphStore over ZK/K8s;
    payloads here are cloudpickled like the reference's serialized
    JobGraphs)."""

    def __init__(self, storage_dir: str):
        self.dir = os.path.join(storage_dir, "jobgraphs")
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, job_id: str) -> str:
        return os.path.join(self.dir, f"{job_id}.job")

    def put(self, job_id: str, job_name: str, graph, config_dict: dict
            ) -> None:
        blob = cloudpickle.dumps(
            {"job_id": job_id, "job_name": job_name, "graph": graph,
             "config": config_dict})
        tmp = self._path(job_id) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self._path(job_id))

    def remove(self, job_id: str) -> None:
        try:
            os.remove(self._path(job_id))
        except OSError:
            pass

    def job_ids(self) -> List[str]:
        return sorted(n[:-4] for n in os.listdir(self.dir)
                      if n.endswith(".job"))

    def get(self, job_id: str) -> Dict[str, Any]:
        with open(self._path(job_id), "rb") as f:
            return cloudpickle.loads(f.read())


# ---------------------------------------------------------------------------
# Blob store
# ---------------------------------------------------------------------------


class BlobStore:
    """Content-addressed artifact store with a local cache
    (reference: runtime/blob/BlobServer + PermanentBlobCache). Keys are
    sha256 of the content, so distribution is idempotent; every read —
    cache hit or store fetch — is verified against the key, and a
    corrupted cache entry falls back to a store re-fetch."""

    def __init__(self, storage_dir: str,
                 cache_dir: Optional[str] = None):
        self.dir = os.path.join(storage_dir, "blobs")
        os.makedirs(self.dir, exist_ok=True)
        self.cache_dir = cache_dir
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)

    def put(self, data: bytes) -> str:
        key = hashlib.sha256(data).hexdigest()
        path = os.path.join(self.dir, key)
        if not os.path.exists(path):
            tmp = path + f".tmp-{uuid.uuid4().hex[:8]}"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        return key

    def get(self, key: str) -> bytes:
        if self.cache_dir:
            cached = os.path.join(self.cache_dir, key)
            if os.path.exists(cached):
                with open(cached, "rb") as f:
                    data = f.read()
                # the content-addressed contract holds for cache hits too:
                # a corrupted cache entry falls through to a store re-fetch
                if hashlib.sha256(data).hexdigest() == key:
                    return data
                try:
                    os.remove(cached)
                except OSError:
                    pass
        with open(os.path.join(self.dir, key), "rb") as f:
            data = f.read()
        if hashlib.sha256(data).hexdigest() != key:
            raise IOError(f"blob {key} failed content verification")
        if self.cache_dir:
            tmp = os.path.join(self.cache_dir,
                               f".{key}.tmp-{uuid.uuid4().hex[:8]}")
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, os.path.join(self.cache_dir, key))
        return data

    def exists(self, key: str) -> bool:
        return os.path.exists(os.path.join(self.dir, key))

    def delete(self, key: str) -> None:
        try:
            os.remove(os.path.join(self.dir, key))
        except OSError:
            pass
