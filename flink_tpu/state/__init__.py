from flink_tpu.state.keygroups import (
    KeyGroupAssignment,
    KeyGroupRange,
    assign_key_groups,
    compute_key_group_range,
    key_group_to_operator_index,
    hash_keys_to_i64,
)
from flink_tpu.state.slot_table import SlotTable

__all__ = [
    "KeyGroupAssignment",
    "KeyGroupRange",
    "assign_key_groups",
    "compute_key_group_range",
    "key_group_to_operator_index",
    "hash_keys_to_i64",
    "SlotTable",
]
