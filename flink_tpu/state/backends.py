"""State backend SPI — where keyed state lives.

reference: StateBackend SPI (flink-runtime/.../state/StateBackend.java)
with HashMapStateBackend (JVM heap) and EmbeddedRocksDBStateBackend
(native, beyond-memory) selected by ``state.backend``.

Re-design: in this architecture every backend runs the SAME batched
kernels — what a backend actually decides is *placement*: which device
holds the accumulator arrays. XLA computation follows data placement, so
committing the state to a device is the whole backend:

- ``tpu-slot-table`` (default): accumulators live on the accelerator
  (HBM); scatters/fires are device kernels; the spill tier extends
  beyond HBM (state.slot-table.max-device-slots).
- ``host-heap``: accumulators committed to the host CPU device —
  NOTHING crosses the accelerator link. The HashMapStateBackend role:
  right for small-state jobs where a tunneled accelerator's per-dispatch
  latency exceeds the compute (control-plane-heavy pipelines, tests).

Third-party backends register a placement factory under a name
(``register_state_backend``) — e.g. a second accelerator, or a specific
device of a multi-chip host.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

#: name -> () -> Optional[jax.Device] (None = default device)
_BACKENDS: Dict[str, Callable] = {}


def register_state_backend(name: str, placement_factory: Callable) -> None:
    """Register a backend: ``placement_factory() -> jax.Device | None``."""
    _BACKENDS[name] = placement_factory


def _default_placement():
    return None  # the platform's default device (accelerator when present)


def _host_placement():
    import jax

    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None  # no CPU backend registered: fall back to default


register_state_backend("tpu-slot-table", _default_placement)
register_state_backend("host-heap", _host_placement)


def resolve_placement(backend: str):
    """The device keyed-state accumulators commit to (None = default)."""
    try:
        factory = _BACKENDS[backend]
    except KeyError:
        raise ValueError(
            f"unknown state.backend {backend!r}; registered: "
            f"{sorted(_BACKENDS)}") from None
    return factory()
