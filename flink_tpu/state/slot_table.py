"""Device-resident key->slot state table.

This replaces the reference's per-key state backends (Heap hash table:
flink-runtime/.../state/heap/CopyOnWriteStateTable.java; RocksDB column
families keyed by keyGroup+key+namespace:
flink-state-backends/flink-statebackend-rocksdb/.../RocksDBKeyedStateBackend.java)
with a split design natural to XLA's static-shape world:

- **Host** (``HostSlotIndex``): a hash index ``(key_id, namespace) -> slot``
  plus per-slot metadata (key id, namespace) in NumPy arrays, a free list,
  and a namespace -> slots registry for O(fired) window expiry.
- **Device** (``SlotTable``): the accumulator leaves — flat ``[capacity]``
  jnp arrays updated by donated scatter kernels (see
  ``flink_tpu.windowing.aggregates``). The mesh-sharded variant
  (``flink_tpu.parallel.sharded_windower``) keeps one HostSlotIndex per
  shard and a single ``[num_shards, capacity]`` device array sharded over
  the key-group mesh axis.

Slot 0 is reserved as the identity slot (padding target). Capacity grows by
doubling (a bounded number of XLA recompiles). The namespace doubles as the
window/slice id, mirroring the reference's namespace-per-window keyed state
(reference: streaming/runtime/operators/windowing/WindowOperator.java:382
``windowState.setCurrentNamespace(window)``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from flink_tpu.state.keygroups import assign_key_groups
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.ops.segment_ops import pad_bucket_size, pad_i32, sticky_bucket


def unique_pairs(
    key_ids: np.ndarray, namespaces: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized grouping of (key, namespace) pairs.

    Returns (unique_keys, unique_namespaces, inverse) where
    ``inverse[i]`` is the unique-pair index of record ``i``.
    """
    n = len(key_ids)
    if n == 0:
        e = np.empty(0, dtype=np.int64)
        return e, e.copy(), np.empty(0, dtype=np.int64)
    order = np.lexsort((key_ids, namespaces))
    ks, ns = key_ids[order], namespaces[order]
    new_group = np.empty(n, dtype=bool)
    new_group[0] = True
    new_group[1:] = (ks[1:] != ks[:-1]) | (ns[1:] != ns[:-1])
    group_of_sorted = np.cumsum(new_group) - 1
    inverse = np.empty(n, dtype=np.int64)
    inverse[order] = group_of_sorted
    first_pos = order[new_group]
    return key_ids[first_pos], namespaces[first_pos], inverse


class _NamespaceRegistry:
    """Shared namespace -> slots registry (O(namespaces), pure Python).

    Mixed into both slot-index implementations so slice expiry and the
    chunk-merge bookkeeping exist exactly once.
    """

    def _init_registry(self) -> None:
        self._ns_slots: Dict[int, List[np.ndarray]] = {}

    @property
    def namespaces(self) -> List[int]:
        return list(self._ns_slots.keys())

    def slots_for_namespace(self, ns: int) -> np.ndarray:
        chunks = self._ns_slots.get(ns)
        if not chunks:
            return np.empty(0, dtype=np.int32)
        if len(chunks) > 1:
            merged = np.concatenate(chunks)
            self._ns_slots[ns] = [merged]
            return merged
        return chunks[0]

    def _registry_drain(self, namespaces: List[int]) -> Optional[np.ndarray]:
        """Remove and return all slots registered under ``namespaces``."""
        freed: List[np.ndarray] = []
        for ns in namespaces:
            chunks = self._ns_slots.pop(ns, None)
            if chunks:
                freed.extend(chunks)
        if not freed:
            return None
        return np.concatenate(freed)


class HostSlotIndex(_NamespaceRegistry):
    """Host half of the state table: (key, ns) -> slot mapping + metadata.

    Capacity growth is signalled via ``on_grow(old, new)`` so the owner can
    resize device arrays in lockstep.
    """

    def __init__(self, capacity: int,
                 on_grow: Optional[Callable[[int, int], None]] = None,
                 growable: bool = True,
                 full_hint: str = "raise state.slot-table.capacity") -> None:
        self.capacity = max(int(capacity), 1024)
        self.on_grow = on_grow
        self.growable = growable
        self.full_hint = full_hint
        self._index: Dict[Tuple[int, int], int] = {}
        self.slot_key = np.zeros(self.capacity, dtype=np.int64)
        self.slot_ns = np.zeros(self.capacity, dtype=np.int64)
        self.slot_used = np.zeros(self.capacity, dtype=bool)
        self._free: List[int] = list(range(self.capacity - 1, 0, -1))
        self._init_registry()

    @property
    def num_used(self) -> int:
        return int(self.slot_used.sum())

    def lookup_or_insert(self, key_ids: np.ndarray,
                         namespaces: np.ndarray) -> np.ndarray:
        """Vectorized (key, ns) -> slot mapping; allocates missing slots.

        The per-unique-pair Python dict probe is the only scalar loop on the
        hot path (bounded by distinct keys per batch, not records).
        """
        uk, un, inverse = unique_pairs(
            np.asarray(key_ids, dtype=np.int64),
            np.asarray(namespaces, dtype=np.int64),
        )
        m = len(uk)
        uslots = np.empty(m, dtype=np.int32)
        index = self._index
        new_by_ns: Dict[int, List[int]] = {}
        for j in range(m):
            pair = (int(uk[j]), int(un[j]))
            slot = index.get(pair)
            if slot is None:
                slot = self._allocate()
                index[pair] = slot
                self.slot_key[slot] = pair[0]
                self.slot_ns[slot] = pair[1]
                self.slot_used[slot] = True
                new_by_ns.setdefault(pair[1], []).append(slot)
            uslots[j] = slot
        for ns, slots in new_by_ns.items():
            self._ns_slots.setdefault(ns, []).append(
                np.asarray(slots, dtype=np.int32))
        return uslots[inverse]

    def lookup(self, key_ids: np.ndarray,
               namespaces: np.ndarray) -> np.ndarray:
        """Read-only probe: slot per pair, -1 where absent (the queryable-
        state point-lookup path — never allocates)."""
        keys = np.asarray(key_ids, dtype=np.int64)
        nss = np.asarray(namespaces, dtype=np.int64)
        out = np.empty(len(keys), dtype=np.int32)
        index = self._index
        for j in range(len(keys)):
            out[j] = index.get((int(keys[j]), int(nss[j])), -1)
        return out

    def _allocate(self) -> int:
        if not self._free:
            self._grow()
        return self._free.pop()

    def _grow(self) -> None:
        if not self.growable:
            raise RuntimeError(
                f"slot table full (capacity={self.capacity}) and not "
                f"growable; {self.full_hint}")
        old = self.capacity
        new_capacity = old * 2
        self.slot_key = np.concatenate(
            [self.slot_key, np.zeros(old, dtype=np.int64)])
        self.slot_ns = np.concatenate(
            [self.slot_ns, np.zeros(old, dtype=np.int64)])
        self.slot_used = np.concatenate(
            [self.slot_used, np.zeros(old, dtype=bool)])
        self._free.extend(range(new_capacity - 1, old - 1, -1))
        self.capacity = new_capacity
        if self.on_grow is not None:
            self.on_grow(old, new_capacity)

    def free_namespaces(self, namespaces: List[int]) -> Optional[np.ndarray]:
        """Release all slots of the given namespaces. Returns freed slots."""
        slots = self._registry_drain(namespaces)
        if slots is None:
            return None
        index = self._index
        sk, sn = self.slot_key, self.slot_ns
        for s in slots.tolist():
            index.pop((int(sk[s]), int(sn[s])), None)
        self.slot_used[slots] = False
        self._free.extend(slots.tolist())
        return slots

    def used_slots(self) -> np.ndarray:
        return np.nonzero(self.slot_used)[0]


class NativeSlotIndex(_NamespaceRegistry):
    """C++-backed drop-in for HostSlotIndex (see native/slotmap.cpp).

    The batch probe loop runs in native code; slot metadata lives in
    C++-owned arrays exposed to NumPy zero-copy. The namespace -> slots
    registry stays in Python (it is O(namespaces), not O(records)).
    """

    def __init__(self, capacity: int,
                 on_grow: Optional[Callable[[int, int], None]] = None,
                 growable: bool = True,
                 full_hint: str = "raise state.slot-table.capacity") -> None:
        from flink_tpu.native import load_slotmap

        self._lib = load_slotmap()
        assert self._lib is not None
        self.capacity = max(int(capacity), 1024)
        self.on_grow = on_grow
        self.growable = growable
        self.full_hint = full_hint
        max_cap = (1 << 28) if growable else self.capacity
        self._h = self._lib.sm_create(self.capacity, max_cap)
        self._wrap_views()
        self._init_registry()

    def _wrap_views(self) -> None:
        import ctypes

        cap = int(self._lib.sm_capacity(self._h))
        self.capacity = cap
        self.slot_key = np.ctypeslib.as_array(
            self._lib.sm_slot_keys(self._h), shape=(cap,))
        self.slot_ns = np.ctypeslib.as_array(
            self._lib.sm_slot_namespaces(self._h), shape=(cap,))
        self.slot_used = np.ctypeslib.as_array(
            self._lib.sm_slot_used(self._h), shape=(cap,)).view(bool)

    def __del__(self):  # pragma: no cover - finalizer
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.sm_destroy(h)
            self._h = None

    @property
    def num_used(self) -> int:
        return int(self._lib.sm_used(self._h))

    def lookup_or_insert(self, key_ids: np.ndarray,
                         namespaces: np.ndarray) -> np.ndarray:
        import ctypes

        keys = np.ascontiguousarray(key_ids, dtype=np.int64)
        nss = np.ascontiguousarray(namespaces, dtype=np.int64)
        n = len(keys)
        out = np.empty(n, dtype=np.int32)
        is_new = np.empty(n, dtype=np.uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        old_cap = self.capacity
        rc = self._lib.sm_lookup_or_insert(
            self._h, n,
            keys.ctypes.data_as(i64p), nss.ctypes.data_as(i64p),
            out.ctypes.data_as(i32p), is_new.ctypes.data_as(u8p))
        if rc < 0:
            raise RuntimeError(
                f"slot table full (capacity={self.capacity}) and not "
                f"growable; {self.full_hint}")
        if rc > 0:
            self._wrap_views()
            if self.on_grow is not None:
                self.on_grow(old_cap, self.capacity)
        new_mask = is_new.view(bool)
        if new_mask.any():
            new_slots = out[new_mask]
            new_ns = nss[new_mask]
            # group new slots by namespace: sort + split (O(n log n), not a
            # per-namespace mask scan)
            order = np.argsort(new_ns, kind="stable")
            sorted_ns = new_ns[order]
            sorted_slots = new_slots[order]
            boundaries = np.nonzero(np.diff(sorted_ns))[0] + 1
            chunks = np.split(sorted_slots, boundaries)
            firsts = np.concatenate(([0], boundaries))
            reg = self._ns_slots
            for ns, chunk in zip(sorted_ns[firsts].tolist(), chunks):
                reg.setdefault(ns, []).append(chunk)
        return out

    def lookup(self, key_ids: np.ndarray,
               namespaces: np.ndarray) -> np.ndarray:
        """Read-only probe via the native table: -1 where absent."""
        import ctypes

        keys = np.ascontiguousarray(key_ids, dtype=np.int64)
        nss = np.ascontiguousarray(namespaces, dtype=np.int64)
        out = np.empty(len(keys), dtype=np.int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        self._lib.sm_lookup(self._h, len(keys),
                            keys.ctypes.data_as(i64p),
                            nss.ctypes.data_as(i64p),
                            out.ctypes.data_as(i32p))
        return out

    def free_namespaces(self, namespaces: List[int]) -> Optional[np.ndarray]:
        import ctypes

        drained = self._registry_drain(namespaces)
        if drained is None:
            return None
        slots = np.ascontiguousarray(drained, dtype=np.int32)
        keys = np.ascontiguousarray(self.slot_key[slots])
        nss = np.ascontiguousarray(self.slot_ns[slots])
        out = np.empty(len(slots), dtype=np.int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        n = self._lib.sm_erase(
            self._h, len(slots),
            keys.ctypes.data_as(i64p), nss.ctypes.data_as(i64p),
            out.ctypes.data_as(i32p))
        return out[:n]

    def used_slots(self) -> np.ndarray:
        return np.nonzero(self.slot_used)[0]


def make_slot_index(capacity: int, on_grow=None, growable: bool = True,
                    full_hint: str = "raise state.slot-table.capacity"):
    """Native index when the C++ library is available, else pure Python."""
    from flink_tpu.native import slotmap_available

    cls = NativeSlotIndex if slotmap_available() else HostSlotIndex
    return cls(capacity, on_grow=on_grow, growable=growable,
               full_hint=full_hint)


class SlotTable:
    """Single-device keyed windowed state (host index + device accumulators)."""

    def __init__(
        self,
        agg: AggregateFunction,
        capacity: int = 1 << 16,
        max_parallelism: int = 128,
        device=None,
    ) -> None:
        self.agg = agg
        self.max_parallelism = max_parallelism
        self.device = device
        self.index = make_slot_index(capacity, on_grow=self._grow_device)
        self.accs: Tuple[jnp.ndarray, ...] = agg.init_accumulators(
            self.index.capacity)
        # buckets are sticky: once a program of bucket B compiled, nearby
        # smaller batches reuse it instead of compiling a smaller program
        # (XLA compiles dominate cold cost; padded lanes hit identity slot 0;
        # sticky_bucket caps the padding waste at 4x)
        self._fire_bucket = 0
        self._scatter_bucket = 0
        self._reset_bucket = 0
        # incremental-snapshot bookkeeping (reference: the dirty-tracking
        # role of RocksDB's memtable/SST-diff in
        # RocksIncrementalSnapshotStrategy — here a host bitmap of slots
        # touched since the last snapshot + the namespaces freed since)
        self._dirty = np.zeros(self.index.capacity, dtype=bool)
        self._freed_ns: List[int] = []
        self._gather_bucket = 0

    # ------------------------------------------------------------------ info

    @property
    def capacity(self) -> int:
        return self.index.capacity

    @property
    def num_used(self) -> int:
        return self.index.num_used

    @property
    def namespaces(self) -> List[int]:
        return self.index.namespaces

    # ------------------------------------------------------------- main path

    def lookup_or_insert(self, key_ids: np.ndarray,
                         namespaces: np.ndarray) -> np.ndarray:
        return self.index.lookup_or_insert(key_ids, namespaces)

    def _grow_device(self, old: int, new: int) -> None:
        self.accs = tuple(
            jnp.concatenate(
                [a, jnp.full((new - old,), leaf.identity, dtype=leaf.dtype)])
            for a, leaf in zip(self.accs, self.agg.leaves)
        )
        self._dirty = np.concatenate(
            [self._dirty, np.zeros(new - old, dtype=bool)])

    def scatter(self, slots: np.ndarray, values: Tuple[np.ndarray, ...]) -> None:
        """Accumulate a batch: one donated XLA scatter per leaf."""
        n = len(slots)
        if n == 0:
            return
        self._dirty[slots] = True
        size = sticky_bucket(n, self._scatter_bucket)
        self._scatter_bucket = size
        padded_slots = pad_i32(slots, size, fill=0)
        padded_vals = self.agg.pad_input_values(values, size)
        self.accs = self.agg._scatter_jit(self.accs, padded_slots, padded_vals)

    # ------------------------------------------------------------- fire path

    def slots_for_namespace(self, ns: int) -> np.ndarray:
        return self.index.slots_for_namespace(ns)

    def keys_of_slots(self, slots: np.ndarray) -> np.ndarray:
        return self.index.slot_key[slots]

    def fire(self, slot_matrix: np.ndarray) -> Dict[str, np.ndarray]:
        """Merge+finish a [num_windows, k] matrix of slice slots.

        Missing slices point at slot 0 (identity). Returns host result
        columns.
        """
        w, k = slot_matrix.shape
        if w == 0:
            return {name: np.empty(0) for name in self.agg.output_names}
        wp = sticky_bucket(w, self._fire_bucket, minimum=64)
        self._fire_bucket = wp
        return self._fire_padded(slot_matrix, wp)

    def _fire_padded(self, slot_matrix: np.ndarray,
                     bucket: int) -> Dict[str, np.ndarray]:
        w, k = slot_matrix.shape
        padded = np.zeros((bucket, k), dtype=np.int32)
        padded[:w] = slot_matrix
        out = self.agg._fire_jit(self.accs, jnp.asarray(padded))
        return {name: np.asarray(col)[:w] for name, col in out.items()}

    def mark_dirty(self, slots: np.ndarray) -> None:
        """For external kernels that mutate ``accs`` directly (e.g. session
        merges): keep incremental snapshots correct."""
        self._dirty[slots] = True

    def free_index_only(self, namespaces: List[int]) -> Optional[np.ndarray]:
        """Release the host index entries of namespaces whose device values
        were already neutralized by a caller-owned kernel (session merges).
        Still records tombstones for incremental snapshots."""
        slots = self.index.free_namespaces(namespaces)
        self._freed_ns.extend(int(n) for n in namespaces)
        if slots is not None:
            self._dirty[slots] = False
        return slots

    def free_namespaces(self, namespaces: List[int]) -> None:
        """Release all slots of the given namespaces (windows fully fired)."""
        slots = self.index.free_namespaces(namespaces)
        self._freed_ns.extend(int(n) for n in namespaces)
        if slots is None:
            return
        self._dirty[slots] = False
        size = sticky_bucket(len(slots), self._reset_bucket)
        self._reset_bucket = size
        self.accs = self.agg._reset_jit(self.accs, pad_i32(slots, size, fill=0))

    # ------------------------------------------------------------ point query

    def query(self, key_id: int, namespace: Optional[int] = None
              ) -> Dict[int, Dict[str, float]]:
        """Point lookup for queryable state: finished result columns for the
        key, per namespace (reference: flink-queryable-state KvState lookup
        against the live backend). Read-only — including the sticky fire
        bucket, which belongs to the hot window-fire path."""
        nss = ([int(namespace)] if namespace is not None
               else [int(n) for n in self.index.namespaces])
        if not nss:
            return {}
        keys = np.full(len(nss), int(key_id), dtype=np.int64)
        slots = self.index.lookup(keys, np.asarray(nss, dtype=np.int64))
        hit = slots >= 0
        if not hit.any():
            return {}
        matrix = slots[hit][:, None].astype(np.int32)
        results = self._fire_padded(matrix,
                                    pad_bucket_size(len(matrix), minimum=64))
        out: Dict[int, Dict[str, float]] = {}
        hit_nss = [n for n, h in zip(nss, hit) if h]
        for i, ns in enumerate(hit_nss):
            out[ns] = {name: col[i].item()
                       for name, col in results.items()}
        return out

    def query_windows(self, key_id: int, assigner
                      ) -> Dict[int, Dict[str, float]]:
        """Point lookup composing WINDOW results from per-slice partial
        accumulators (slice sharing: a sliding window's value = merge of k
        slices — reference: SliceAssigners slice/window mapping). Returns
        {window_end -> finished result columns} for the key. Read-only."""
        live_ns = np.asarray([int(n) for n in self.index.namespaces],
                             dtype=np.int64)
        if len(live_ns) == 0:
            return {}
        keys = np.full(len(live_ns), int(key_id), dtype=np.int64)
        slots = self.index.lookup(keys, live_ns)
        hit = slots >= 0
        if not hit.any():
            return {}
        slice_slot = {int(n): int(s)
                      for n, s, h in zip(live_ns, slots, hit) if h}
        windows = sorted({
            int(w)
            for se in slice_slot
            for w in assigner.window_ends_for_slice(se)})
        k = max(len(assigner.slice_ends_for_window(w)) for w in windows)
        matrix = np.zeros((len(windows), k), dtype=np.int32)
        for i, w in enumerate(windows):
            for j, se in enumerate(assigner.slice_ends_for_window(w)):
                matrix[i, j] = slice_slot.get(int(se), 0)
        results = self._fire_padded(
            matrix, pad_bucket_size(len(matrix), minimum=64))
        return {w: {name: col[i].item() for name, col in results.items()}
                for i, w in enumerate(windows)}

    # ---------------------------------------------------------- snapshot/restore

    def snapshot(self, reset_dirty: bool = True) -> Dict[str, np.ndarray]:
        """Materialize state as host arrays, filtered to used slots.

        The snapshot is *logical* (key, ns, key_group, leaf values) — slot
        numbers are not part of the format, so restore can re-shard by key
        group (the reference's rescale-by-key-group-range contract,
        reference: KeyGroupRangeAssignment.java + state/restore pipeline).
        With ``reset_dirty`` (the default) the snapshot establishes a new
        incremental base; savepoints pass False so a mid-run savepoint does
        not silently shrink the next delta checkpoint's contents.
        """
        used = self.index.used_slots()
        accs_host = [np.asarray(a) for a in self.accs]
        key_ids = self.index.slot_key[used]
        if reset_dirty:
            self._dirty[:] = False
            self._freed_ns.clear()
        return {
            "key_id": key_ids,
            "namespace": self.index.slot_ns[used],
            "key_group": assign_key_groups(key_ids, self.max_parallelism),
            **{
                f"leaf_{i}": accs_host[i][used]
                for i in range(len(self.accs))
            },
        }

    def snapshot_delta(self) -> Dict[str, np.ndarray]:
        """Incremental snapshot: only rows dirtied since the last snapshot
        plus the namespaces freed since (tombstones). Restore applies deltas
        on top of the last full snapshot
        (reference: RocksIncrementalSnapshotStrategy — upload only new SSTs;
        here: transfer only dirty slots off the device)."""
        dirty_used = np.nonzero(self._dirty & self.index.slot_used)[0] \
            .astype(np.int32)
        freed = np.asarray(sorted(set(self._freed_ns)), dtype=np.int64)
        n = len(dirty_used)
        if n:
            size = sticky_bucket(n, self._gather_bucket)
            self._gather_bucket = size
            gathered = self.agg._gather_jit(
                self.accs, jnp.asarray(pad_i32(dirty_used, size, fill=0)))
            leaves = [np.asarray(g)[:n] for g in gathered]
        else:
            leaves = [np.empty(0, dtype=l.dtype) for l in self.agg.leaves]
        key_ids = self.index.slot_key[dirty_used]
        out = {
            "__delta__": np.asarray(True),
            "key_id": key_ids,
            "namespace": self.index.slot_ns[dirty_used],
            "key_group": assign_key_groups(key_ids, self.max_parallelism),
            "freed_namespaces": freed,
            **{f"leaf_{i}": leaves[i] for i in range(len(leaves))},
        }
        self._dirty[:] = False
        self._freed_ns.clear()
        return out

    def restore(self, snap: Dict[str, np.ndarray],
                key_group_filter=None) -> None:
        """Load a logical snapshot, optionally keeping only owned key groups."""
        key_ids = np.asarray(snap["key_id"], dtype=np.int64)
        namespaces = np.asarray(snap["namespace"], dtype=np.int64)
        groups = np.asarray(snap["key_group"], dtype=np.int32)
        leaves = [np.asarray(snap[f"leaf_{i}"]) for i in range(len(self.agg.leaves))]
        if key_group_filter is not None:
            mask = np.array([g in key_group_filter for g in groups], dtype=bool)
            key_ids, namespaces = key_ids[mask], namespaces[mask]
            leaves = [l[mask] for l in leaves]
        slots = self.lookup_or_insert(key_ids, namespaces)
        accs_host = [np.array(a) for a in self.accs]  # writable copies
        for acc, vals in zip(accs_host, leaves):
            acc[slots] = vals
        self.accs = tuple(jnp.asarray(a) for a in accs_host)
        # restored state IS the new incremental base
        self._dirty[:] = False
        self._freed_ns.clear()
