"""Device-resident key->slot state table.

This replaces the reference's per-key state backends (Heap hash table:
flink-runtime/.../state/heap/CopyOnWriteStateTable.java; RocksDB column
families keyed by keyGroup+key+namespace:
flink-state-backends/flink-statebackend-rocksdb/.../RocksDBKeyedStateBackend.java)
with a split design natural to XLA's static-shape world:

- **Host** (``HostSlotIndex``): a hash index ``(key_id, namespace) -> slot``
  plus per-slot metadata (key id, namespace) in NumPy arrays, a free list,
  and a namespace -> slots registry for O(fired) window expiry.
- **Device** (``SlotTable``): the accumulator leaves — flat ``[capacity]``
  jnp arrays updated by donated scatter kernels (see
  ``flink_tpu.windowing.aggregates``). The mesh-sharded variant
  (``flink_tpu.parallel.sharded_windower``) keeps one HostSlotIndex per
  shard and a single ``[num_shards, capacity]`` device array sharded over
  the key-group mesh axis.

Slot 0 is reserved as the identity slot (padding target). Capacity grows by
doubling (a bounded number of XLA recompiles). The namespace doubles as the
window/slice id, mirroring the reference's namespace-per-window keyed state
(reference: streaming/runtime/operators/windowing/WindowOperator.java:382
``windowState.setCurrentNamespace(window)``).
"""

from __future__ import annotations

import ctypes as _ct
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.state.keygroups import assign_key_groups
from flink_tpu.stateplane import flat_fence
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.ops.segment_ops import (
    pad_bucket_size,
    pad_i32,
    pad_values,
    sticky_bucket,
)


from flink_tpu.core.annotations import internal


def _coerce_snapshot_leaf(
        arr: np.ndarray, want: np.dtype) -> Optional[np.ndarray]:
    """Cast a snapshot leaf to the aggregate's dtype iff value-preserving.

    Returns the cast array, or None when the cast would lose values.
    Integer targets get an exact range (and integrality) check instead of
    relying on numpy's overflow-on-cast side effect; float targets use
    roundtrip equality (NaN-tolerant) with overflow warnings suppressed —
    an out-of-range value becomes inf and fails the roundtrip.
    """
    if np.issubdtype(want, np.integer):
        info = np.iinfo(want)
        if np.issubdtype(arr.dtype, np.floating):
            if not np.all(np.isfinite(arr)):
                return None
            if not np.all(np.trunc(arr) == arr):
                return None
            # exact endpoints in float space: info.min and info.max + 1 are
            # +-2**(bits-1), exactly representable in float64 — a plain
            # `arr <= info.max` would round the bound UP and let 2**63 wrap
            lo, hi = float(info.min), float(info.max + 1)
            if not np.all((arr >= lo) & (arr < hi)):
                return None
        else:
            # integer -> integer: compare extremes as Python ints (exact,
            # immune to uint64/int64 promotion pitfalls)
            if int(arr.min()) < info.min or int(arr.max()) > info.max:
                return None
        return arr.astype(want)
    with np.errstate(over="ignore", invalid="ignore"):
        cast = arr.astype(want)
        equal_nan = np.issubdtype(arr.dtype, np.inexact)
        back = cast.astype(arr.dtype)
        ok = (np.array_equal(back, arr, equal_nan=True) if equal_nan
              else np.array_equal(back, arr))
        return cast if ok else None


def unique_pairs(
    key_ids: np.ndarray, namespaces: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized grouping of (key, namespace) pairs.

    Returns (unique_keys, unique_namespaces, inverse) where
    ``inverse[i]`` is the unique-pair index of record ``i``.
    """
    n = len(key_ids)
    if n == 0:
        e = np.empty(0, dtype=np.int64)
        return e, e.copy(), np.empty(0, dtype=np.int64)
    order = np.lexsort((key_ids, namespaces))
    ks, ns = key_ids[order], namespaces[order]
    new_group = np.empty(n, dtype=bool)
    new_group[0] = True
    new_group[1:] = (ks[1:] != ks[:-1]) | (ns[1:] != ns[:-1])
    group_of_sorted = np.cumsum(new_group) - 1
    inverse = np.empty(n, dtype=np.int64)
    inverse[order] = group_of_sorted
    first_pos = order[new_group]
    return key_ids[first_pos], namespaces[first_pos], inverse


class SlotTableFullError(RuntimeError):
    """Device slot budget exhausted — the owner may evict and retry."""


def verify_slot_hints(index, key_ids: np.ndarray, namespaces: np.ndarray,
                      hints: np.ndarray) -> np.ndarray:
    """Resolve folded device-slot hints against the index's OWN metadata
    views: a hint is taken iff the index currently maps exactly that
    (key, ns) pair at that slot. Returns int32 slots with -1 where the
    hint is absent or stale — callers fall back to the hash probe there.

    Correct by construction: ``slot_key``/``slot_ns``/``slot_used`` ARE
    the table's contents, so a passing verification can never name a
    wrong row — a fold gone stale (eviction, fire, reshard, restore)
    fails the compare and costs one fallback probe, never a wrong
    gather. This is what makes the metadata-plane slot fold a pure
    cache: no invalidation protocol, no correctness coupling."""
    native = getattr(index, "verify_hints", None)
    if native is not None:
        return native(key_ids, namespaces, hints)
    hints = np.asarray(hints, dtype=np.int32)
    out = np.full(len(hints), -1, dtype=np.int32)
    hv = hints >= 0
    if not hv.any():
        return out
    hs = hints[hv]
    cap = index.capacity
    safe = np.minimum(hs, cap - 1)
    ok = ((hs < cap)
          & index.slot_used[safe]
          & (index.slot_key[safe]
             == np.asarray(key_ids, dtype=np.int64)[hv])
          & (index.slot_ns[safe]
             == np.asarray(namespaces, dtype=np.int64)[hv]))
    out[hv] = np.where(ok, hs, np.int32(-1))
    return out


def resolve_slot_hints(index, key_ids: np.ndarray, namespaces: np.ndarray,
                       hints: np.ndarray, skip=None) -> np.ndarray:
    """The verify-then-probe resolve every hint consumer runs: take the
    verified folds, hash-probe the unresolved remainder, and leave -1
    for pairs the index does not hold. ``skip``: rows the caller KNOWS
    cannot be present (fresh session ids) — they keep -1 without paying
    the probe. One copy of the pattern for the resolve, the fire and
    the single-device table paths."""
    pre = verify_slot_hints(index, key_ids, namespaces, hints)
    probe = pre < 0
    if skip is not None:
        probe &= ~skip
    if probe.any():
        pre[probe] = index.lookup(
            np.asarray(key_ids)[probe], np.asarray(namespaces)[probe])
    return pre


class _NamespaceRegistry:
    """Shared namespace -> slots registry (O(namespaces), pure Python).

    Mixed into both slot-index implementations so slice expiry and the
    chunk-merge bookkeeping exist exactly once.
    """

    def _init_registry(self, track: bool = True) -> None:
        self._ns_slots: Dict[int, List[np.ndarray]] = {}
        #: False = the owner frees by SLOT and never asks for a
        #: namespace's slot list — skip the per-namespace bookkeeping
        #: entirely (the session tables: one row per ns, millions of ns;
        #: registry upkeep was O(sessions) Python per batch)
        self._track_ns = track

    @property
    def namespaces(self) -> List[int]:
        return list(self._ns_slots.keys())

    def slots_for_namespace(self, ns: int) -> np.ndarray:
        chunks = self._ns_slots.get(ns)
        if not chunks:
            return np.empty(0, dtype=np.int32)
        if len(chunks) > 1:
            merged = np.concatenate(chunks)
            self._ns_slots[ns] = [merged]
            return merged
        return chunks[0]

    def _registry_drain(self, namespaces: List[int]) -> Optional[np.ndarray]:
        """Remove and return all slots registered under ``namespaces``."""
        freed: List[np.ndarray] = []
        for ns in namespaces:
            chunks = self._ns_slots.pop(ns, None)
            if chunks:
                freed.extend(chunks)
        if not freed:
            return None
        return np.concatenate(freed)

    def _registry_remove_slots(self, slots: np.ndarray,
                               namespaces: np.ndarray) -> None:
        """Remove individual slots from their namespaces' chunk lists
        (TTL expiry and paged eviction free by slot, not by whole
        namespace)."""
        if not self._track_ns:
            return
        uniq, counts = np.unique(namespaces, return_counts=True)
        slots_per_ns = dict(zip(uniq.tolist(), counts.tolist()))
        for ns, freed_here in slots_per_ns.items():
            chunks = self._ns_slots.get(int(ns))
            if not chunks:
                continue
            total = (len(chunks[0]) if len(chunks) == 1
                     else sum(len(c) for c in chunks))
            if total <= freed_here:
                # every slot of the namespace is being freed (the session
                # case: one slot per sid) — O(1), no membership scan
                self._ns_slots.pop(int(ns), None)
                continue
            merged = np.concatenate(chunks) if len(chunks) > 1 \
                else chunks[0]
            kept = merged[~np.isin(merged, slots)]
            if len(kept):
                self._ns_slots[int(ns)] = [kept]
            else:
                self._ns_slots.pop(int(ns), None)


class HostSlotIndex(_NamespaceRegistry):
    """Host half of the state table: (key, ns) -> slot mapping + metadata.

    Capacity growth is signalled via ``on_grow(old, new)`` so the owner can
    resize device arrays in lockstep.
    """

    def __init__(self, capacity: int,
                 on_grow: Optional[Callable[[int, int], None]] = None,
                 growable: bool = True,
                 full_hint: str = "raise state.slot-table.capacity",
                 max_capacity: int = 0,
                 track_namespaces: bool = True) -> None:
        self.capacity = max(int(capacity), 1024)
        self.on_grow = on_grow
        self.growable = growable
        self.full_hint = full_hint
        self.max_capacity = int(max_capacity or 0)
        self._index: Dict[Tuple[int, int], int] = {}
        self.slot_key = np.zeros(self.capacity, dtype=np.int64)
        self.slot_ns = np.zeros(self.capacity, dtype=np.int64)
        self.slot_used = np.zeros(self.capacity, dtype=bool)
        self._free: List[int] = list(range(self.capacity - 1, 0, -1))
        self._init_registry(track_namespaces)

    @property
    def num_used(self) -> int:
        return int(self.slot_used.sum())

    def lookup_or_insert(self, key_ids: np.ndarray,
                         namespaces: np.ndarray) -> np.ndarray:
        """Vectorized (key, ns) -> slot mapping; allocates missing slots.

        The per-unique-pair Python dict probe is the only scalar loop on the
        hot path (bounded by distinct keys per batch, not records).
        """
        uk, un, inverse = unique_pairs(
            np.asarray(key_ids, dtype=np.int64),
            np.asarray(namespaces, dtype=np.int64),
        )
        m = len(uk)
        uslots = np.empty(m, dtype=np.int32)
        index = self._index
        new_by_ns: Dict[int, List[int]] = {}
        for j in range(m):
            pair = (int(uk[j]), int(un[j]))
            slot = index.get(pair)
            if slot is None:
                slot = self._allocate()
                index[pair] = slot
                self.slot_key[slot] = pair[0]
                self.slot_ns[slot] = pair[1]
                self.slot_used[slot] = True
                new_by_ns.setdefault(pair[1], []).append(slot)
            uslots[j] = slot
        if self._track_ns:
            for ns, slots in new_by_ns.items():
                self._ns_slots.setdefault(ns, []).append(
                    np.asarray(slots, dtype=np.int32))
        return uslots[inverse]

    def lookup(self, key_ids: np.ndarray,
               namespaces: np.ndarray) -> np.ndarray:
        """Read-only probe: slot per pair, -1 where absent (the queryable-
        state point-lookup path — never allocates)."""
        keys = np.asarray(key_ids, dtype=np.int64)
        nss = np.asarray(namespaces, dtype=np.int64)
        out = np.empty(len(keys), dtype=np.int32)
        index = self._index
        for j in range(len(keys)):
            out[j] = index.get((int(keys[j]), int(nss[j])), -1)
        return out

    def _allocate(self) -> int:
        if not self._free:
            self._grow()
        return self._free.pop()

    def _grow(self) -> None:
        # grow by doubling, clamped to max_capacity (matches the native
        # index): refusing a partial last step would make free_headroom
        # over-report and strand a mid-batch insert
        if not self.growable or (
                self.max_capacity and self.capacity >= self.max_capacity):
            raise SlotTableFullError(
                f"slot table full (capacity={self.capacity}) and not "
                f"growable; {self.full_hint}")
        old = self.capacity
        new_capacity = old * 2
        if self.max_capacity:
            new_capacity = min(new_capacity, self.max_capacity)
        extra = new_capacity - old
        self.slot_key = np.concatenate(
            [self.slot_key, np.zeros(extra, dtype=np.int64)])
        self.slot_ns = np.concatenate(
            [self.slot_ns, np.zeros(extra, dtype=np.int64)])
        self.slot_used = np.concatenate(
            [self.slot_used, np.zeros(extra, dtype=bool)])
        self._free.extend(range(new_capacity - 1, old - 1, -1))
        self.capacity = new_capacity
        if self.on_grow is not None:
            self.on_grow(old, new_capacity)

    def free_namespaces(self, namespaces: List[int]) -> Optional[np.ndarray]:
        """Release all slots of the given namespaces. Returns freed slots."""
        slots = self._registry_drain(namespaces)
        if slots is None:
            return None
        index = self._index
        sk, sn = self.slot_key, self.slot_ns
        for s in slots.tolist():
            index.pop((int(sk[s]), int(sn[s])), None)
        self.slot_used[slots] = False
        self._free.extend(slots.tolist())
        return slots

    def free_slots(self, slots: np.ndarray, keys=None, nss=None) -> None:
        """Release individual slots (TTL expiry — by entry, not by
        namespace). ``keys``/``nss`` let a caller that already holds the
        slots' pair columns skip the per-slot metadata gather."""
        slots = np.asarray(slots, dtype=np.int32)
        if not len(slots):
            return
        if nss is None:
            nss = self.slot_ns[slots]
        self._registry_remove_slots(slots, nss)
        if keys is None:
            keys = self.slot_key[slots]
        index = self._index
        for k, v in zip(np.asarray(keys).tolist(),
                        np.asarray(nss).tolist()):
            index.pop((int(k), int(v)), None)
        self.slot_used[slots] = False
        self._free.extend(slots.tolist())

    def used_slots(self) -> np.ndarray:
        return np.nonzero(self.slot_used)[0]

    def free_headroom(self) -> int:
        """Slots still allocatable (incl. future growth). Slot 0 reserved."""
        if self.growable:
            limit = self.max_capacity if self.max_capacity else (1 << 60)
        else:
            limit = self.capacity
        return limit - 1 - self.num_used


#: hoisted ctypes pointer types for the native probe wrappers — one
#: construction per process instead of several per call (the native
#: index is probed tens of thousands of times per bench second)
_I64P = _ct.POINTER(_ct.c_int64)
_I32P = _ct.POINTER(_ct.c_int32)
_U8P = _ct.POINTER(_ct.c_uint8)


class NativeSlotIndex(_NamespaceRegistry):
    """C++-backed drop-in for HostSlotIndex (see native/slotmap.cpp).

    The batch probe loop runs in native code; slot metadata lives in
    C++-owned arrays exposed to NumPy zero-copy. The namespace -> slots
    registry stays in Python (it is O(namespaces), not O(records)).
    """

    def __init__(self, capacity: int,
                 on_grow: Optional[Callable[[int, int], None]] = None,
                 growable: bool = True,
                 full_hint: str = "raise state.slot-table.capacity",
                 max_capacity: int = 0,
                 track_namespaces: bool = True) -> None:
        from flink_tpu.native import load_slotmap

        self._lib = load_slotmap()
        assert self._lib is not None
        self.capacity = max(int(capacity), 1024)
        self.on_grow = on_grow
        self.growable = growable
        self.full_hint = full_hint
        self.max_capacity = int(max_capacity or 0)
        max_cap = (self.max_capacity or (1 << 28)) if growable \
            else self.capacity
        self._h = self._lib.sm_create(self.capacity, max_cap)
        self._wrap_views()
        self._init_registry(track_namespaces)

    def _wrap_views(self) -> None:
        import ctypes

        cap = int(self._lib.sm_capacity(self._h))
        self.capacity = cap
        self.slot_key = np.ctypeslib.as_array(
            self._lib.sm_slot_keys(self._h), shape=(cap,))
        self.slot_ns = np.ctypeslib.as_array(
            self._lib.sm_slot_namespaces(self._h), shape=(cap,))
        self.slot_used = np.ctypeslib.as_array(
            self._lib.sm_slot_used(self._h), shape=(cap,)).view(bool)

    def __del__(self):  # pragma: no cover - finalizer
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.sm_destroy(h)
            self._h = None

    @property
    def num_used(self) -> int:
        return int(self._lib.sm_used(self._h))

    def lookup_or_insert(self, key_ids: np.ndarray,
                         namespaces: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(key_ids, dtype=np.int64)
        nss = np.ascontiguousarray(namespaces, dtype=np.int64)
        n = len(keys)
        out = np.empty(n, dtype=np.int32)
        is_new = np.empty(n, dtype=np.uint8)
        old_cap = self.capacity
        rc = self._lib.sm_lookup_or_insert(
            self._h, n,
            keys.ctypes.data_as(_I64P), nss.ctypes.data_as(_I64P),
            out.ctypes.data_as(_I32P), is_new.ctypes.data_as(_U8P))
        if rc < 0:
            raise SlotTableFullError(
                f"slot table full (capacity={self.capacity}) and not "
                f"growable; {self.full_hint}")
        if rc > 0:
            self._wrap_views()
            if self.on_grow is not None:
                self.on_grow(old_cap, self.capacity)
        new_mask = is_new.view(bool)
        if new_mask.any() and self._track_ns:
            new_slots = out[new_mask]
            new_ns = nss[new_mask]
            # group new slots by namespace: sort + split (O(n log n), not a
            # per-namespace mask scan)
            order = np.argsort(new_ns, kind="stable")
            sorted_ns = new_ns[order]
            sorted_slots = new_slots[order]
            boundaries = np.nonzero(np.diff(sorted_ns))[0] + 1
            chunks = np.split(sorted_slots, boundaries)
            firsts = np.concatenate(([0], boundaries))
            reg = self._ns_slots
            for ns, chunk in zip(sorted_ns[firsts].tolist(), chunks):
                reg.setdefault(ns, []).append(chunk)
        return out

    def pane_ingest(self, key_ids: np.ndarray, timestamps: np.ndarray,
                    offset: int, width: int, max_uniq: int = 4096):
        """Fused pane-table ingest (native/slotmap.cpp sm_pane_ingest):
        one native sweep computes slice ends, the key -> column probe
        (namespace 0) and the distinct-slice-end plan that previously
        took five separate numpy passes. Returns (cols, sinv, uniq,
        max_col) or None when the batch has pathologically many distinct
        slice ends (caller falls back to the unfused path)."""
        import ctypes

        keys = np.ascontiguousarray(key_ids, dtype=np.int64)
        ts = np.ascontiguousarray(timestamps, dtype=np.int64)
        n = len(keys)
        cols = np.empty(n, dtype=np.int32)
        is_new = np.empty(n, dtype=np.uint8)
        sinv = np.empty(n, dtype=np.int32)
        uniq = np.empty(max_uniq, dtype=np.int64)
        out_k = ctypes.c_int64()
        out_max_col = ctypes.c_int64()
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        old_cap = self.capacity
        rc = self._lib.sm_pane_ingest(
            self._h, n, keys.ctypes.data_as(i64p), ts.ctypes.data_as(i64p),
            int(offset), int(width), int(max_uniq),
            cols.ctypes.data_as(i32p), is_new.ctypes.data_as(u8p),
            sinv.ctypes.data_as(i32p), uniq.ctypes.data_as(i64p),
            ctypes.byref(out_k), ctypes.byref(out_max_col))
        if rc == -2:
            return None
        if rc < 0:
            raise SlotTableFullError(
                f"slot table full (capacity={self.capacity}) and not "
                f"growable; {self.full_hint}")
        if rc > 0:
            self._wrap_views()
            if self.on_grow is not None:
                self.on_grow(old_cap, self.capacity)
        new_mask = is_new.view(bool)
        if new_mask.any():
            # all pane-table entries live in namespace 0
            self._ns_slots.setdefault(0, []).append(cols[new_mask])
        return cols, sinv, uniq[:out_k.value], int(out_max_col.value)

    def flat_fuse(self, cols: np.ndarray, sinv: np.ndarray,
                  rowmap: np.ndarray, capacity: int) -> np.ndarray:
        """flat[i] = rowmap[sinv[i]] * capacity + cols[i] as int32, in one
        native pass (sm_flat_fuse)."""
        import ctypes

        n = len(cols)
        out = np.empty(n, dtype=np.int32)
        rowmap = np.ascontiguousarray(rowmap, dtype=np.int64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        self._lib.sm_flat_fuse(
            n, cols.ctypes.data_as(i32p), sinv.ctypes.data_as(i32p),
            rowmap.ctypes.data_as(i64p), int(capacity),
            out.ctypes.data_as(i32p))
        return out

    def lookup(self, key_ids: np.ndarray,
               namespaces: np.ndarray) -> np.ndarray:
        """Read-only probe via the native table: -1 where absent."""
        keys = np.ascontiguousarray(key_ids, dtype=np.int64)
        nss = np.ascontiguousarray(namespaces, dtype=np.int64)
        out = np.empty(len(keys), dtype=np.int32)
        self._lib.sm_lookup(self._h, len(keys),
                            keys.ctypes.data_as(_I64P),
                            nss.ctypes.data_as(_I64P),
                            out.ctypes.data_as(_I32P))
        return out

    def verify_hints(self, key_ids: np.ndarray, namespaces: np.ndarray,
                     hints: np.ndarray) -> np.ndarray:
        """Native form of :func:`verify_slot_hints` — one direct-indexed
        C pass over the table's own metadata (sm_verify)."""
        keys = np.ascontiguousarray(key_ids, dtype=np.int64)
        nss = np.ascontiguousarray(namespaces, dtype=np.int64)
        hints = np.ascontiguousarray(hints, dtype=np.int32)
        out = np.empty(len(keys), dtype=np.int32)
        self._lib.sm_verify(self._h, len(keys),
                            keys.ctypes.data_as(_I64P),
                            nss.ctypes.data_as(_I64P),
                            hints.ctypes.data_as(_I32P),
                            out.ctypes.data_as(_I32P))
        return out

    def free_namespaces(self, namespaces: List[int]) -> Optional[np.ndarray]:
        drained = self._registry_drain(namespaces)
        if drained is None:
            return None
        slots = np.ascontiguousarray(drained, dtype=np.int32)
        keys = np.ascontiguousarray(self.slot_key[slots])
        nss = np.ascontiguousarray(self.slot_ns[slots])
        out = np.empty(len(slots), dtype=np.int32)
        n = self._lib.sm_erase(
            self._h, len(slots),
            keys.ctypes.data_as(_I64P), nss.ctypes.data_as(_I64P),
            out.ctypes.data_as(_I32P))
        return out[:n]

    def free_slots(self, slots: np.ndarray, keys=None, nss=None) -> None:
        """Release individual slots (TTL expiry) via the native erase.
        ``keys``/``nss`` let a caller that already holds the slots' pair
        columns skip the per-slot metadata gathers."""
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        if not len(slots):
            return
        if nss is None:
            nss = self.slot_ns[slots]
        self._registry_remove_slots(slots, nss)
        if keys is None:
            keys = self.slot_key[slots]
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        nss = np.ascontiguousarray(nss, dtype=np.int64)
        out = np.empty(len(slots), dtype=np.int32)
        self._lib.sm_erase(
            self._h, len(slots),
            keys.ctypes.data_as(_I64P), nss.ctypes.data_as(_I64P),
            out.ctypes.data_as(_I32P))

    def used_slots(self) -> np.ndarray:
        return np.nonzero(self.slot_used)[0]

    def free_headroom(self) -> int:
        """Slots still allocatable (incl. future growth). Slot 0 reserved."""
        if self.growable:
            limit = self.max_capacity if self.max_capacity else (1 << 28)
        else:
            limit = self.capacity
        return limit - 1 - self.num_used


def make_slot_index(capacity: int, on_grow=None, growable: bool = True,
                    full_hint: str = "raise state.slot-table.capacity",
                    max_capacity: int = 0,
                    track_namespaces: bool = True):
    """Native index when the C++ library is available, else pure Python."""
    from flink_tpu.native import slotmap_available

    cls = NativeSlotIndex if slotmap_available() else HostSlotIndex
    return cls(capacity, on_grow=on_grow, growable=growable,
               full_hint=full_hint, max_capacity=max_capacity,
               track_namespaces=track_namespaces)


class SpillTier:
    """Beyond-HBM state: whole namespaces evicted from the device table.

    Two levels — host memory, then a filesystem directory (any ``core.fs``
    scheme) once the host budget is exceeded. This is the role RocksDB /
    ForSt play for the reference (state far larger than memory,
    reference: RocksDBKeyedStateBackend.java;
    ForStStateExecutor.java:149 batch contract); the unit of movement is a
    namespace (window slice / session id), not a key, so reloads are one
    batched put kernel.
    """

    def __init__(self, spill_dir: Optional[str] = None,
                 host_max_bytes: int = 0):
        self.spill_dir = spill_dir
        self.host_max_bytes = host_max_bytes
        self._host: Dict[int, Dict[str, np.ndarray]] = {}
        self._host_bytes = 0
        self._fs: Dict[int, str] = {}  # ns -> file path
        self._dirty: set = set()  # namespaces changed since last snapshot
        self._seq = 0
        #: ns -> row count, maintained across host/fs moves so batch
        #: planners can estimate reload cost without touching the fs
        self._rows: Dict[int, int] = {}

    def __len__(self) -> int:
        return len(self._host) + len(self._fs)

    def __contains__(self, ns: int) -> bool:
        return ns in self._host or ns in self._fs

    @property
    def namespaces(self) -> List[int]:
        return list(self._host) + list(self._fs)

    @staticmethod
    def _entry_bytes(entry: Dict[str, np.ndarray]) -> int:
        return sum(a.nbytes for a in entry.values())

    def put(self, ns: int, entry: Dict[str, np.ndarray],
            dirty: bool) -> None:
        assert ns not in self, f"namespace {ns} spilled twice"
        self._host[ns] = entry
        self._host_bytes += self._entry_bytes(entry)
        self._rows[ns] = len(entry["key_id"])
        if dirty:
            self._dirty.add(ns)
        self._maybe_overflow_to_fs()

    def rows(self, ns: int) -> int:
        """Row count of a spilled namespace (0 if absent) — an O(1) read
        that never touches the filesystem."""
        return self._rows.get(ns, 0)

    def _maybe_overflow_to_fs(self) -> None:
        if not self.spill_dir or self.host_max_bytes <= 0:
            return
        from flink_tpu.core.fs import get_filesystem

        fs, local = get_filesystem(self.spill_dir)
        fs.mkdirs(local)
        while self._host_bytes > self.host_max_bytes and self._host:
            ns, entry = next(iter(self._host.items()))
            import io as _io

            buf = _io.BytesIO()
            np.savez(buf, **entry)
            self._seq += 1
            path = f"{local.rstrip('/')}/ns-{ns}-{self._seq}.npz"
            with fs.open(path, "wb") as f:
                f.write(buf.getvalue())
            self._fs[ns] = f"{self._scheme_prefix()}{path}"
            self._host_bytes -= self._entry_bytes(entry)
            del self._host[ns]

    def _scheme_prefix(self) -> str:
        if self.spill_dir and "://" in self.spill_dir:
            return self.spill_dir.split("://", 1)[0] + "://"
        return ""

    def pop(self, ns: int) -> Optional[Dict[str, np.ndarray]]:
        """Remove and return a spilled namespace (reload or free)."""
        entry = self._host.pop(ns, None)
        if entry is not None:
            self._host_bytes -= self._entry_bytes(entry)
        elif ns in self._fs:
            from flink_tpu.core.fs import get_filesystem

            path = self._fs.pop(ns)
            fs, local = get_filesystem(path)
            with fs.open(local, "rb") as f:
                loaded = np.load(f)
                entry = {k: loaded[k] for k in loaded.files}
            fs.delete(local)
        was_dirty = ns in self._dirty
        self._dirty.discard(ns)
        self._rows.pop(ns, None)
        if entry is not None:
            entry["__was_dirty__"] = np.asarray(was_dirty)
        return entry

    def peek(self, ns: int) -> Optional[Dict[str, np.ndarray]]:
        """Read a spilled namespace without removing it (snapshots)."""
        entry = self._host.get(ns)
        if entry is not None:
            return entry
        if ns in self._fs:
            from flink_tpu.core.fs import get_filesystem

            fs, local = get_filesystem(self._fs[ns])
            with fs.open(local, "rb") as f:
                loaded = np.load(f)
                return {k: loaded[k] for k in loaded.files}
        return None

    def drop(self, ns: int) -> None:
        """Discard a spilled namespace (window fully fired elsewhere)."""
        self.pop(ns)

    def discard(self, ns: int) -> None:
        """Delete a spilled namespace WITHOUT loading it — a page in
        the fs tier is unlinked, never read/deserialized (the hot-path
        reap of fully-dead pages must not pay a wasted disk read)."""
        entry = self._host.pop(ns, None)
        if entry is not None:
            self._host_bytes -= self._entry_bytes(entry)
        elif ns in self._fs:
            from flink_tpu.core.fs import get_filesystem

            path = self._fs.pop(ns)
            fs, local = get_filesystem(path)
            fs.delete(local)
        self._dirty.discard(ns)
        self._rows.pop(ns, None)

    def dirty_namespaces(self) -> List[int]:
        return list(self._dirty)

    def clear_dirty(self) -> None:
        self._dirty.clear()


@internal
class SlotTable:
    """Single-device keyed windowed state (host index + device accumulators).

    With ``max_device_slots`` set, the device table is an HBM-bounded cache
    over a host/filesystem ``SpillTier``: when full, the least-recently-
    touched namespaces are evicted wholesale (one gather + one reset
    kernel) and reload transparently on the next access (one put kernel).
    """

    def __init__(
        self,
        agg: AggregateFunction,
        capacity: int = 1 << 16,
        max_parallelism: int = 128,
        device=None,
        max_device_slots: int = 0,
        spill_dir: Optional[str] = None,
        spill_host_max_bytes: int = 0,
        memory=None,
        spill_layout: str = "namespaces",
        track_namespaces: bool = True,
    ) -> None:
        self.agg = agg
        self.max_parallelism = max_parallelism
        self.device = device
        self.max_device_slots = int(max_device_slots or 0)
        if self.max_device_slots:
            capacity = min(capacity, self.max_device_slots)
        #: (MemoryManager, owner) — managed accounting of the device
        #: accumulator footprint (reference: MemoryManager.java pages;
        #: here bytes, reserved at creation and each growth)
        self._memory = memory
        self.spill = SpillTier(spill_dir, spill_host_max_bytes)
        self._ns_touch: Dict[int, int] = {}
        self._touch_clock = 0
        # Spill layout (reference: RocksDBKeyedStateBackend.java —
        # block-granular storage under a small memory budget):
        # - "namespaces" (default): the unit of movement is one namespace
        #   (a window slice shared by many keys) — right when namespaces
        #   are large and few.
        # - "pages": the unit is an EVICTION COHORT of many rows —
        #   right when namespaces are tiny and numerous (sessions: one
        #   row per session id). Residency tracking is slot-granular
        #   (a per-slot touch clock), membership is a sorted array
        #   binary-searched per batch, and spill/reload moves tens of
        #   thousands of rows per entry instead of one. REQUIRES
        #   single-row namespaces (eviction would otherwise split a
        #   namespace across the device/page boundary).
        if spill_layout not in ("namespaces", "pages"):
            raise ValueError(
                f"spill_layout must be 'namespaces' or 'pages', got "
                f"{spill_layout!r}")
        self.spill_layout = spill_layout
        self._paged = spill_layout == "pages" and self.max_device_slots > 0
        if self._paged:
            from flink_tpu.state.paged_spill import PagedSpillMap

            #: membership map + dead set + counters for the paged layout
            #: (flink_tpu.state.paged_spill — shared with the mesh engine)
            self._pmap = PagedSpillMap()
        self.index = make_slot_index(
            capacity, on_grow=self._grow_device,
            max_capacity=self.max_device_slots,
            track_namespaces=track_namespaces,
            full_hint=("state spills to host beyond "
                       "state.slot-table.max-device-slots"
                       if self.max_device_slots
                       else "raise state.slot-table.capacity"))
        self._reserve_rows(self.index.capacity)
        if self._paged:
            # sized AFTER index creation: the index clamps capacity up
            # (>= 1024), and the touch clock must cover every slot
            self._slot_touch = np.zeros(self.index.capacity,
                                        dtype=np.int64)
        self.accs: Tuple[jnp.ndarray, ...] = agg.init_accumulators(
            self.index.capacity)
        if device is not None:
            # the state backend's whole decision (state/backends.py):
            # committing the accumulators pins every kernel that touches
            # them to this device — XLA computation follows placement
            self.accs = tuple(jax.device_put(a, device) for a in self.accs)
        # buckets are sticky: once a program of bucket B compiled, nearby
        # smaller batches reuse it instead of compiling a smaller program
        # (XLA compiles dominate cold cost; padded lanes hit identity slot 0;
        # sticky_bucket caps the padding waste at 4x)
        self._fire_bucket = 0
        self._scatter_bucket = 0
        self._reset_bucket = 0
        # incremental-snapshot bookkeeping (reference: the dirty-tracking
        # role of RocksDB's memtable/SST-diff in
        # RocksIncrementalSnapshotStrategy — here a host bitmap of slots
        # touched since the last snapshot + the namespaces freed since)
        self._dirty = np.zeros(self.index.capacity, dtype=bool)
        self._freed_ns: List[int] = []
        #: per-(key, ns) tombstones from TTL expiry (free_slots) — the
        #: entry-granular analog of _freed_ns for incremental snapshots
        self._freed_pairs: List[Tuple[np.ndarray, np.ndarray]] = []
        self._gather_bucket = 0

    # ------------------------------------------------------------- memory

    def _row_bytes(self) -> int:
        return sum(np.dtype(leaf.dtype).itemsize
                   for leaf in self.agg.leaves)

    def _reserve_rows(self, rows: int) -> None:
        if self._memory is not None:
            manager, owner = self._memory
            manager.reserve(owner, rows * self._row_bytes())

    def release_memory(self) -> None:
        """Return this table's reservation to the pool (dispose path)."""
        if self._memory is not None:
            manager, owner = self._memory
            manager.release(owner, self.index.capacity
                            * self._row_bytes())

    # ------------------------------------------------------------------ info

    @property
    def capacity(self) -> int:
        return self.index.capacity

    @property
    def num_used(self) -> int:
        return self.index.num_used

    @property
    def namespaces(self) -> List[int]:
        """All live namespaces — device-resident AND spilled."""
        if getattr(self.index, "_track_ns", True):
            resident = self.index.namespaces
        else:  # registry-free: derive from the used-slot metadata
            used = self.index.used_slots()
            resident = np.unique(self.index.slot_ns[used]).tolist()
        if self._paged:
            return resident + self._pmap.live_ns().tolist()
        return resident + self.spill.namespaces

    # ------------------------------------------------------------- main path

    def lookup_or_insert(self, key_ids: np.ndarray,
                         namespaces: np.ndarray,
                         _pairs=None, hints=None) -> np.ndarray:
        if self.max_device_slots and self._paged:
            return self._lookup_or_insert_paged(key_ids, namespaces,
                                                _pairs, hints)
        if self.max_device_slots:
            # ``_pairs`` lets upsert() hand down its already-computed
            # unique (key, ns) pairs instead of re-sorting the batch
            if _pairs is None:
                uk, un, _ = unique_pairs(
                    np.asarray(key_ids, dtype=np.int64),
                    np.asarray(namespaces, dtype=np.int64))
            else:
                uk, un = _pairs
            touched = np.unique(un)
            self.ensure_resident(touched.tolist())
            self._touch(touched.tolist())
            # headroom pre-check: lookup_or_insert allocates incrementally,
            # so running out MID-batch would leave the index and the
            # namespace registry inconsistent — make room up front for
            # exactly the pairs that are genuinely new (a read-only probe).
            # Under ample headroom (the steady-state common case) skip the
            # probe — len(uk) over-counts but cheaply proves safety.
            if self.index.free_headroom() < len(uk):
                needed = int((self.index.lookup(uk, un) < 0).sum())
                if needed:
                    self._make_headroom(needed,
                                        protect=set(touched.tolist()))
        return self.index.lookup_or_insert(key_ids, namespaces)

    def _make_headroom(self, needed: int, protect: set) -> None:
        while self.index.free_headroom() < needed:
            self._evict_cold(protect=protect)

    # --------------------------------------------------- paged spill layout

    def _lookup_or_insert_paged(self, key_ids, namespaces,
                                _pairs=None, hints=None) -> np.ndarray:
        """Slot-clock variant of the spill-aware lookup: resident rows of
        THIS batch are stamped with a fresh clock (protecting them from
        the eviction the batch itself triggers), missing pairs reload by
        page, then the plain index insert runs.

        ``hints``: folded device slots from the session-metadata plane,
        aligned with ``key_ids`` — which must then already be UNIQUE
        pairs (the session contract: one row per sid). Verified hints
        skip the hash probe; the result path inserts only the misses,
        which is state-identical to the full lookup_or_insert (hits
        never allocate) but pays the native probe only for rows whose
        fold went stale."""
        key_ids = np.asarray(key_ids, dtype=np.int64)
        namespaces = np.asarray(namespaces, dtype=np.int64)
        self._touch_clock += 1
        clock = self._touch_clock
        if hints is not None:
            uk, un = key_ids, namespaces
            pre = resolve_slot_hints(self.index, uk, un, hints)
        else:
            if _pairs is None:
                uk, un, _ = unique_pairs(key_ids, namespaces)
            else:
                uk, un = _pairs
            pre = self.index.lookup(uk, un)
        hit = pre >= 0
        self._slot_touch[pre[hit]] = clock
        missing = ~hit
        if missing.any() and len(self._sp_ns):
            self._reload_pages_for(un[missing], clock)
            # re-probe: reloaded rows are resident now (fresh sessions
            # stay missing); skipping this when the reload happened to
            # drain the spilled map would overcount `needed` and evict
            # or fail spuriously
            pre = self.index.lookup(uk, un)
            missing = pre < 0
        needed = int(missing.sum())
        if needed and self.index.free_headroom() < needed:
            self._make_headroom_paged(needed)
        if hints is not None:
            # unique pairs: hits are final, only the misses insert
            slots = pre.astype(np.int32, copy=True)
            if missing.any():
                slots[missing] = self.index.lookup_or_insert(
                    uk[missing], un[missing])
        else:
            slots = self.index.lookup_or_insert(key_ids, namespaces)
        self._slot_touch[slots] = clock
        return slots

    # compat READ views over the PagedSpillMap (tests and older callers
    # inspect the raw arrays; the map itself is the shared
    # implementation). No setters: assigning a raw array would desync
    # the tombstone mask (sp_dead) the map keeps alongside — mutate
    # through the map's API instead.
    @property
    def _sp_ns(self) -> np.ndarray:
        return self._pmap.sp_ns

    @property
    def _sp_page(self) -> np.ndarray:
        return self._pmap.sp_page

    def spill_counters(self) -> Dict[str, int]:
        """Paged spill traffic counters (zeros when not paged)."""
        from flink_tpu.state.paged_spill import PagedSpillMap

        if self._paged:
            return self._pmap.counters()
        return PagedSpillMap.zero_counters()

    def _sp_sort(self) -> None:
        self._pmap.sort()

    def _spilled_mask(self, nss: np.ndarray) -> np.ndarray:
        """Vectorized membership: which of ``nss`` are spilled."""
        return self._pmap.spilled_mask(nss)

    def _reload_pages_for(self, nss: np.ndarray, clock: int) -> None:
        """Reload the requested rows from their pages — extraction by
        stored row index; the pages' other rows stay put as lazy
        tombstones and compact only past the dead-fraction threshold
        (see flink_tpu.state.paged_spill)."""
        from flink_tpu.state.paged_spill import reload_rows_for

        rl = reload_rows_for(self.spill, self._pmap, nss,
                             [l.dtype for l in self.agg.leaves])
        if rl is None:
            return
        keys, rns, dirty, vals = rl
        n = len(keys)
        if self.index.free_headroom() < n:
            self._make_headroom_paged(n)
        slots = self.index.lookup_or_insert(keys, rns)
        size = sticky_bucket(n, self._scatter_bucket)
        self._scatter_bucket = size
        padded_slots = pad_i32(slots, size, fill=0)
        pvals = tuple(
            np.concatenate([v, np.full(size - n, l.identity,
                                       dtype=l.dtype)])
            for v, l in zip(vals, self.agg.leaves))
        self.accs = self.agg._put_jit(
            self.accs, jnp.asarray(padded_slots),
            tuple(jnp.asarray(v) for v in pvals))
        # reloaded rows keep their dirtiness (not snapshotted since) and
        # take the current clock — the cohort is likely about to fire
        self._dirty[slots] = dirty
        self._slot_touch[slots] = clock

    def _make_headroom_paged(self, needed: int) -> None:
        while self.index.free_headroom() < needed:
            self._evict_cold_paged()

    def _drop_spilled_sessions(self, nss: np.ndarray) -> None:
        """Mark spilled sessions dead; reap pages left with no live
        mapping entries (flink_tpu.state.paged_spill)."""
        if not self._paged:
            return
        from flink_tpu.state.paged_spill import drop_spilled_sessions

        drop_spilled_sessions(self.spill, self._pmap,
                              np.asarray(nss, dtype=np.int64))

    def _evict_cold_paged(self) -> None:
        """Evict the coldest slots (touch < current clock) as ONE page:
        one gather + one reset kernel + one spill entry, however many
        sessions the cohort spans."""
        used = self.index.used_slots()
        touch = self._slot_touch[used]
        evictable = used[touch < self._touch_clock]
        if len(evictable) == 0:
            raise SlotTableFullError(
                "device slot budget exhausted and every resident row was "
                "touched by the current batch — raise "
                "state.slot-table.max-device-slots or reduce batch size")
        target = min(max(self.index.capacity // 8, 1024), len(evictable))
        et = self._slot_touch[evictable]
        if target < len(evictable):
            sel = np.argpartition(et, target - 1)[:target]
            chosen = evictable[sel]
        else:
            chosen = evictable
        chosen = np.asarray(chosen, dtype=np.int32)
        n = len(chosen)
        size = sticky_bucket(n, self._gather_bucket)
        self._gather_bucket = size
        gathered = self.agg._gather_jit(
            self.accs, jnp.asarray(pad_i32(chosen, size, fill=0)))
        from flink_tpu.state.paged_spill import spill_page

        gathered_host = jax.device_get(gathered)  # ONE batched D2H
        entry = {
            "key_id": np.asarray(self.index.slot_key[chosen]),
            "ns": np.asarray(self.index.slot_ns[chosen]),
            "dirty": self._dirty[chosen].copy(),
            **{f"leaf_{i}": g[:n]
               for i, g in enumerate(gathered_host)},
        }
        spill_page(self.spill, self._pmap, entry)
        self.index.free_slots(chosen)
        self._dirty[chosen] = False
        rsize = sticky_bucket(n, self._reset_bucket)
        self._reset_bucket = rsize
        self.accs = self.agg._reset_jit(
            self.accs, pad_i32(chosen, rsize, fill=0))

    def upsert(self, key_ids: np.ndarray, namespaces: np.ndarray,
               values: Tuple[np.ndarray, ...],
               valued: bool = False) -> None:
        """Spill-safe accumulate: when one batch's working set exceeds the
        device budget, it is processed in namespace groups so only one
        group must be resident at a time (a single namespace whose key set
        alone exceeds the budget is the irreducible limit of
        namespace-granular spill and fails loudly).

        ``valued`` marks locally pre-aggregated input (one explicit value
        per leaf per row; see flink_tpu.runtime.local_agg) — folded with
        scatter_valued instead of the map_input scatter."""
        emit = self.scatter_valued if valued else self.scatter
        namespaces = np.asarray(namespaces, dtype=np.int64)
        if self.max_device_slots:
            # slots are consumed per unique (key, ns) PAIR, not per record
            # — chunk only when the pair working set exceeds the budget
            pair_k, pair_ns, _ = unique_pairs(
                np.asarray(key_ids, dtype=np.int64), namespaces)
            uniq_ns, counts = np.unique(pair_ns, return_counts=True)
            budget = max(self.max_device_slots // 2, 1024)
            if len(uniq_ns) > 1 and len(pair_ns) > budget:
                groups: List[List[int]] = []
                cur: List[int] = []
                cur_n = 0
                for ns, c in zip(uniq_ns.tolist(), counts.tolist()):
                    if cur and cur_n + c > budget:
                        groups.append(cur)
                        cur, cur_n = [], 0
                    cur.append(ns)
                    cur_n += c
                groups.append(cur)
                for g in groups:
                    mask = np.isin(namespaces, g)
                    pmask = np.isin(pair_ns, g)
                    slots = self.lookup_or_insert(
                        key_ids[mask], namespaces[mask],
                        _pairs=(pair_k[pmask], pair_ns[pmask]))
                    emit(slots, tuple(np.asarray(v)[mask]
                                      for v in values))
                return
            slots = self.lookup_or_insert(key_ids, namespaces,
                                          _pairs=(pair_k, pair_ns))
            emit(slots, values)
            return
        slots = self.lookup_or_insert(key_ids, namespaces)
        emit(slots, values)

    # ------------------------------------------------------------ spill tier

    def _touch(self, namespaces: List[int]) -> None:
        self._touch_clock += 1
        clock = self._touch_clock
        for ns in namespaces:
            self._ns_touch[int(ns)] = clock

    def ensure_resident(self, namespaces: List[int]) -> None:
        """Reload any spilled namespaces among ``namespaces`` back onto the
        device — ALL reloads batch into one insert + one put kernel (a
        session workload reloads thousands of one-row namespaces at once).
        Transparent to callers: after this, the index serves them like any
        resident namespace."""
        if not self.max_device_slots or len(self.spill) == 0:
            return
        todo = [int(ns) for ns in namespaces if int(ns) in self.spill]
        if not todo:
            return
        protect = set(int(n) for n in namespaces)
        key_chunks: List[np.ndarray] = []
        ns_chunks: List[np.ndarray] = []
        dirty_chunks: List[np.ndarray] = []
        leaf_chunks: List[List[np.ndarray]] = [[] for _ in self.agg.leaves]
        for ns in todo:
            entry = self.spill.pop(ns)
            m = len(entry["key_id"])
            if m == 0:
                continue
            key_chunks.append(np.asarray(entry["key_id"], dtype=np.int64))
            ns_chunks.append(np.full(m, ns, dtype=np.int64))
            dirty_chunks.append(np.full(
                m, bool(entry.get("__was_dirty__", False)), dtype=bool))
            for i, l in enumerate(self.agg.leaves):
                leaf_chunks[i].append(
                    np.asarray(entry[f"leaf_{i}"], dtype=l.dtype))
        if not key_chunks:
            return
        key_ids = np.concatenate(key_chunks)
        nss = np.concatenate(ns_chunks)
        was_dirty = np.concatenate(dirty_chunks)
        n = len(key_ids)
        self._make_headroom(n, protect=protect)
        slots = self.index.lookup_or_insert(key_ids, nss)
        size = sticky_bucket(n, self._scatter_bucket)
        self._scatter_bucket = size
        padded_slots = pad_i32(slots, size, fill=0)
        vals = tuple(
            np.concatenate([
                np.concatenate(leaf_chunks[i]),
                np.full(size - n, l.identity, dtype=l.dtype)])
            for i, l in enumerate(self.agg.leaves))
        self.accs = self.agg._put_jit(
            self.accs, jnp.asarray(padded_slots),
            tuple(jnp.asarray(v) for v in vals))
        # reloaded rows keep their dirtiness: rows dirty at spill time have
        # not been in any snapshot since
        self._dirty[slots] = was_dirty
        self._touch(todo)

    def _evict_cold(self, protect: set) -> None:
        """Evict the least-recently-touched namespaces to the spill tier
        until a workable fraction of the device table is free — ONE gather
        + ONE reset kernel for the whole eviction batch, however many
        namespaces it spans."""
        target_free = max(self.index.capacity // 8, 1024)
        candidates = sorted(
            (ns for ns in self.index.namespaces if int(ns) not in protect),
            key=lambda ns: self._ns_touch.get(int(ns), 0))
        if not candidates:
            raise SlotTableFullError(
                "device slot budget exhausted and every namespace in the "
                "current batch is protected — raise "
                "state.slot-table.max-device-slots or reduce batch size")
        chosen: List[Tuple[int, np.ndarray]] = []
        freed = 0
        for ns in candidates:
            if freed >= target_free:
                break
            slots = self.index.slots_for_namespace(int(ns))
            chosen.append((int(ns), slots))
            freed += len(slots)
        empty = [ns for ns, s in chosen if len(s) == 0]
        if empty:
            self.index.free_namespaces(empty)
        chosen = [(ns, s) for ns, s in chosen if len(s) > 0]
        if not chosen:
            return
        all_slots = np.concatenate([s for _, s in chosen])
        n = len(all_slots)
        size = sticky_bucket(n, self._gather_bucket)
        self._gather_bucket = size
        gathered = self.agg._gather_jit(
            self.accs, jnp.asarray(pad_i32(all_slots, size, fill=0)))
        # ONE batched D2H read for all leaves
        leaves_host = [g[:n] for g in jax.device_get(gathered)]
        off = 0
        for ns, slots in chosen:
            m = len(slots)
            entry = {
                "key_id": np.asarray(self.index.slot_key[slots]),
                **{f"leaf_{i}": leaves_host[i][off:off + m]
                   for i in range(len(leaves_host))},
            }
            self.spill.put(ns, entry,
                           dirty=bool(self._dirty[slots].any()))
            off += m
            self._ns_touch.pop(ns, None)
        # release the device slots: index entries go, values reset to
        # identity. NOT a logical free — no tombstone (rows live on in the
        # spill tier and reappear in snapshots from there).
        self.index.free_namespaces([ns for ns, _ in chosen])
        self._dirty[all_slots] = False
        rsize = sticky_bucket(n, self._reset_bucket)
        self._reset_bucket = rsize
        self.accs = self.agg._reset_jit(
            self.accs, pad_i32(all_slots, rsize, fill=0))

    def _grow_device(self, old: int, new: int) -> None:
        self._reserve_rows(new - old)
        self.accs = tuple(
            jnp.concatenate(
                [a, jnp.full((new - old,), leaf.identity, dtype=leaf.dtype)])
            for a, leaf in zip(self.accs, self.agg.leaves)
        )
        self._dirty = np.concatenate(
            [self._dirty, np.zeros(new - old, dtype=bool)])
        if self._paged:
            self._slot_touch = np.concatenate(
                [self._slot_touch, np.zeros(new - old, dtype=np.int64)])

    def scatter(self, slots: np.ndarray, values: Tuple[np.ndarray, ...]) -> None:
        """Accumulate a batch: one donated XLA scatter per leaf."""
        n = len(slots)
        if n == 0:
            return
        self._dirty[slots] = True
        size = sticky_bucket(n, self._scatter_bucket)
        self._scatter_bucket = size
        padded_slots = pad_i32(slots, size, fill=0)
        padded_vals = self.agg.pad_input_values(values, size)
        self.accs = self.agg._scatter_jit(self.accs, padded_slots, padded_vals)

    def make_fence(self):
        """A tiny non-donated device value enqueued AFTER everything
        dispatched so far: its readiness proves the device (and the
        host->device copies feeding it) caught up to this point. Used to
        bound how far the task loop's async dispatch runs ahead — without
        a bound, fire kernels queue behind seconds of scatter backlog and
        fire latency grows without limit (reference: checkpoint alignment
        bounds in-flight data the same way; here the scarce resource is
        the device queue)."""
        return flat_fence(self.agg.leaves[0].dtype.str)(self.accs[0])

    def scatter_valued(self, slots: np.ndarray,
                       values: Tuple[np.ndarray, ...]) -> None:
        """Merge pre-aggregated partials: every leaf valued, each folded
        by its own reduce kind (two-phase aggregation's global side). Pad
        lanes carry each leaf's identity into the reserved slot 0."""
        n = len(slots)
        if n == 0:
            return
        self._dirty[slots] = True
        size = sticky_bucket(n, self._scatter_bucket)
        self._scatter_bucket = size
        padded_slots = pad_i32(slots, size, fill=0)
        padded_vals = tuple(
            pad_values(np.asarray(v, dtype=l.dtype), size, l.identity)
            for v, l in zip(values, self.agg.leaves))
        self.accs = self.agg._scatter_valued_jit(
            self.accs, padded_slots, padded_vals)

    def upsert_valued(self, key_ids: np.ndarray, namespaces: np.ndarray,
                      values: Tuple[np.ndarray, ...]) -> None:
        """Upsert of locally pre-aggregated rows — upsert() with the
        valued fold, sharing its spill-safe namespace chunking (coalesced
        batch-mode blocks can merge combined rows from many batches, so
        the working set is NOT bounded by one batch's pairs)."""
        self.upsert(key_ids, namespaces, values, valued=True)

    def scatter_signed(self, slots: np.ndarray,
                       values: Tuple[np.ndarray, ...]) -> None:
        """Changelog fold: values carry their sign (+accumulate /
        -retract), every leaf valued (see AggregateFunction.map_input_signed).
        Pad lanes contribute 0 to the reserved identity slot."""
        n = len(slots)
        if n == 0:
            return
        self._dirty[slots] = True
        size = sticky_bucket(n, self._scatter_bucket)
        self._scatter_bucket = size
        padded_slots = pad_i32(slots, size, fill=0)
        padded_vals = tuple(
            pad_values(np.asarray(v, dtype=l.dtype), size, 0)
            for v, l in zip(values, self.agg.leaves))
        self.accs = self.agg._scatter_signed_jit(
            self.accs, padded_slots, padded_vals)

    # ------------------------------------------------------------- fire path

    def slots_for_namespace(self, ns: int) -> np.ndarray:
        return self.index.slots_for_namespace(ns)

    def keys_of_slots(self, slots: np.ndarray) -> np.ndarray:
        return self.index.slot_key[slots]

    def fire(self, slot_matrix: np.ndarray) -> Dict[str, np.ndarray]:
        """Merge+finish a [num_windows, k] matrix of slice slots.

        Missing slices point at slot 0 (identity). Returns host result
        columns.
        """
        w, k = slot_matrix.shape
        if w == 0:
            return {name: np.empty(0) for name in self.agg.output_names}
        out = self.agg._fire_jit(
            self.accs, jnp.asarray(self._pad_fire_matrix(slot_matrix)))
        # ONE batched D2H for all result columns
        return {name: col[:w]
                for name, col in jax.device_get(out).items()}

    def _pad_fire_matrix(self, slot_matrix: np.ndarray) -> np.ndarray:
        """Sticky-bucket zero-pad shared by every fire dispatch (sync and
        async): one padding policy, one compiled-shape family."""
        w, k = slot_matrix.shape
        wp = sticky_bucket(w, self._fire_bucket, minimum=64)
        self._fire_bucket = wp
        padded = np.zeros((wp, k), dtype=np.int32)
        padded[:w] = slot_matrix
        return padded

    def fire_projected(self, slot_matrix: np.ndarray, keys: np.ndarray,
                       projector) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Fire with a device-side FireProjector: merge+finish+project in
        ONE kernel, transferring only the projector's ``num_out`` rows to
        the host instead of the window's full [num_keys] result set (see
        flink_tpu.windowing.fire_projectors — the Q5 hot-items fire drops
        from ~100k transferred rows to k)."""
        w, k = slot_matrix.shape
        if w == 0:
            return np.empty(0, dtype=np.int64), {
                name: np.empty(0) for name in self.agg.output_names}
        pidx, pcols, pvalid = self.agg._fire_project_jit(projector)(
            self.accs, jnp.asarray(self._pad_fire_matrix(slot_matrix)), w)
        sel = np.asarray(pvalid)
        return (keys[np.asarray(pidx)[sel]],
                {name: np.asarray(c)[sel] for name, c in pcols.items()})

    def fire_async(self, slot_matrix: np.ndarray, keys: np.ndarray):
        """Dispatch a fire and return a PendingFire whose harvest yields
        (keys, result columns) — no synchronous device round trip (the
        tunneled-TPU link makes each blocking read ~100 ms; see
        flink_tpu.runtime.pending)."""
        from flink_tpu.runtime.pending import PendingFire

        w, _ = slot_matrix.shape
        if w == 0:
            return None
        out = self.agg._fire_jit(
            self.accs, jnp.asarray(self._pad_fire_matrix(slot_matrix)))
        names = list(out.keys())

        def build(host: List[np.ndarray]):
            return keys, {name: col[:w] for name, col in zip(names, host)}

        return PendingFire([out[n] for n in names], build)

    def fire_projected_async(self, slot_matrix: np.ndarray,
                             keys: np.ndarray, projector):
        """Async-dispatch variant of fire_projected: same kernel, but the
        host read of the projected rows is deferred to harvest time."""
        from flink_tpu.runtime.pending import PendingFire

        w, _ = slot_matrix.shape
        if w == 0:
            return None
        pidx, pcols, pvalid = self.agg._fire_project_jit(projector)(
            self.accs, jnp.asarray(self._pad_fire_matrix(slot_matrix)), w)
        names = list(pcols.keys())

        def build(host: List[np.ndarray]):
            pidx_h, pvalid_h = host[0], host[1]
            sel = pvalid_h
            return (keys[pidx_h[sel]],
                    {name: col[sel]
                     for name, col in zip(names, host[2:])})

        return PendingFire([pidx, pvalid] + [pcols[n] for n in names], build)

    def build_slice_matrix(self, slice_ends: List[int]
                           ) -> Tuple[Optional[np.ndarray],
                                      Optional[np.ndarray]]:
        """(keys, [num_keys, k] slot matrix) for the resident slices of a
        window — missing (key, slice) cells point at the identity slot 0.
        Shared by the device fire path and the hybrid (spill) fire path."""
        per_slice = [(i, self.index.slots_for_namespace(se))
                     for i, se in enumerate(slice_ends)]
        per_slice = [(i, s) for i, s in per_slice if len(s) > 0]
        if not per_slice:
            return None, None
        all_slots = np.concatenate([s for _, s in per_slice])
        all_sidx = np.concatenate(
            [np.full(len(s), i, dtype=np.int32) for i, s in per_slice])
        all_keys = self.index.slot_key[all_slots]
        from flink_tpu.native import group_matrix

        # O(n) native hash grouping beats np.unique's O(n log n) sort on
        # the per-fire hot path; keys come back in first-seen order (the
        # fire result order is key-insensitive)
        native = group_matrix(all_keys, all_slots.astype(np.int32),
                              all_sidx, len(slice_ends))
        if native is not None:
            return native
        keys, inv = np.unique(all_keys, return_inverse=True)
        matrix = np.zeros((len(keys), len(slice_ends)), dtype=np.int32)
        matrix[inv, all_sidx] = all_slots
        return keys, matrix

    def fire_hybrid(self, slice_ends: List[int]
                    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Window fire tolerating spilled slices: device-resident slices
        merge on device (one kernel), spilled slices merge on host, finish
        runs on host over the union. Returns (keys, result columns).

        This keeps the device budget independent of the window's slice
        count — a sliding window whose full slice set exceeds
        max-device-slots still fires correctly (reference: RocksDB windows
        never needed to fit in memory either)."""
        from flink_tpu.ops.segment_ops import HOST_COMBINE

        resident = [se for se in slice_ends if int(se) not in self.spill]
        spilled = [se for se in slice_ends if int(se) in self.spill]
        key_chunks: List[np.ndarray] = []
        leaf_chunks: List[List[np.ndarray]] = [[] for _ in self.agg.leaves]
        # device part
        keys, matrix = self.build_slice_matrix(resident)
        if keys is not None:
            wp = sticky_bucket(len(keys), self._fire_bucket, minimum=64)
            self._fire_bucket = wp
            padded = np.zeros((wp, matrix.shape[1]), dtype=np.int32)
            padded[:len(keys)] = matrix
            merged = self.agg._merge_jit(self.accs, jnp.asarray(padded))
            key_chunks.append(keys)
            for i, m in enumerate(jax.device_get(merged)):
                leaf_chunks[i].append(m[:len(keys)])
        # host part (spilled slices)
        for se in spilled:
            entry = self.spill.peek(int(se))
            if entry is None or len(entry["key_id"]) == 0:
                continue
            key_chunks.append(np.asarray(entry["key_id"], dtype=np.int64))
            for i, l in enumerate(self.agg.leaves):
                leaf_chunks[i].append(
                    np.asarray(entry[f"leaf_{i}"], dtype=l.dtype))
        if not key_chunks:
            return np.empty(0, dtype=np.int64), {}
        all_keys = np.concatenate(key_chunks)
        uniq, inv = np.unique(all_keys, return_inverse=True)
        out_leaves = []
        for i, l in enumerate(self.agg.leaves):
            acc = np.full(len(uniq), l.identity, dtype=l.dtype)
            HOST_COMBINE[l.reduce].at(acc, inv,
                                      np.concatenate(leaf_chunks[i]))
            out_leaves.append(acc)
        finished = self.agg.finish(tuple(out_leaves))
        return uniq, {name: np.asarray(col)
                      for name, col in finished.items()}

    def mark_dirty(self, slots: np.ndarray) -> None:
        """For external kernels that mutate ``accs`` directly (e.g. session
        merges): keep incremental snapshots correct."""
        self._dirty[slots] = True

    def free_index_only(self, namespaces: List[int]) -> Optional[np.ndarray]:
        """Release the host index entries of namespaces whose device values
        were already neutralized by a caller-owned kernel (session merges).
        Still records tombstones for incremental snapshots."""
        slots = self.index.free_namespaces(namespaces)
        self._freed_ns.extend(int(n) for n in namespaces)
        if slots is not None:
            self._dirty[slots] = False
        return slots

    def free_index_only_slots(self, slots: np.ndarray,
                              namespaces) -> None:
        """Slot-addressed free_index_only for registry-free tables: the
        caller (session merge path) already holds the absorbed rows'
        slots; device values were neutralized by its merge kernel."""
        slots = np.asarray(slots, dtype=np.int32)
        self._freed_ns.extend(np.asarray(namespaces,
                                         dtype=np.int64).tolist())
        self.index.free_slots(slots)
        self._dirty[slots] = False

    def free_rows(self, slots: np.ndarray, namespaces) -> None:
        """Slot-addressed free_namespaces (fired sessions): the caller
        resolved the rows this batch, so no registry walk is needed.
        Resets the device values and records namespace tombstones."""
        slots = np.asarray(slots, dtype=np.int32)
        if not len(slots):
            return
        nss = np.asarray(namespaces, dtype=np.int64)
        self._freed_ns.extend(nss.tolist())
        self._drop_spilled_sessions(nss)
        self.index.free_slots(slots)
        self._dirty[slots] = False
        size = sticky_bucket(len(slots), self._reset_bucket)
        self._reset_bucket = size
        self.accs = self.agg._reset_jit(
            self.accs, pad_i32(slots, size, fill=0))

    def free_slots(self, slots: np.ndarray) -> None:
        """Release individual entries (TTL expiry of idle keys).

        Unlike free_namespaces (whole windows), this frees by (key, ns)
        pair and records entry-granular tombstones so incremental
        snapshot chains don't resurrect expired keys (reference:
        TtlStateFactory + RocksDB compaction-filter cleanup)."""
        slots = np.asarray(slots, dtype=np.int32)
        if not len(slots):
            return
        self._freed_pairs.append((self.index.slot_key[slots].copy(),
                                  self.index.slot_ns[slots].copy()))
        self.index.free_slots(slots)
        self._dirty[slots] = False
        size = sticky_bucket(len(slots), self._reset_bucket)
        self._reset_bucket = size
        self.accs = self.agg._reset_jit(self.accs,
                                        pad_i32(slots, size, fill=0))

    def free_namespaces(self, namespaces: List[int]) -> None:
        """Release all slots of the given namespaces (windows fully fired)."""
        slots = self.index.free_namespaces(namespaces)
        self._freed_ns.extend(int(n) for n in namespaces)
        if self._paged:
            self._drop_spilled_sessions(
                np.asarray(namespaces, dtype=np.int64))
        elif len(self.spill):
            for ns in namespaces:
                if int(ns) in self.spill:
                    self.spill.drop(int(ns))
        if not self._paged:
            for ns in namespaces:
                self._ns_touch.pop(int(ns), None)
        if slots is None:
            return
        self._dirty[slots] = False
        size = sticky_bucket(len(slots), self._reset_bucket)
        self._reset_bucket = size
        self.accs = self.agg._reset_jit(self.accs, pad_i32(slots, size, fill=0))

    # ------------------------------------------------------------ point query

    def query(self, key_id: int, namespace: Optional[int] = None
              ) -> Dict[int, Dict[str, float]]:
        """Point lookup for queryable state: finished result columns for the
        key, per namespace (reference: flink-queryable-state KvState lookup
        against the live backend). Read-only — including the sticky fire
        bucket, which belongs to the hot window-fire path."""
        nss = ([int(namespace)] if namespace is not None
               else [int(n) for n in self.namespaces])
        if not nss:
            return {}
        vals = self._key_values_per_namespace(int(key_id), nss)
        out: Dict[int, Dict[str, float]] = {}
        for ns, leaves in vals.items():
            finished = self.agg.finish(leaves)
            out[ns] = {name: np.asarray(col).item()
                       for name, col in finished.items()}
        return out

    def _key_values_per_namespace(
            self, key_id: int, nss: List[int]
    ) -> Dict[int, Tuple[np.ndarray, ...]]:
        """One key's raw accumulator leaves per namespace — device-resident
        namespaces read via one gather kernel, spilled ones from their host
        entries (no residency change: queries must not thrash the cache)."""
        if self._paged:
            sp = self._spilled_mask(np.asarray(nss, dtype=np.int64))
            resident = [ns for ns, s in zip(nss, sp) if not s]
            spilled = [ns for ns, s in zip(nss, sp) if s]
        else:
            resident = [ns for ns in nss if int(ns) not in self.spill]
            spilled = [ns for ns in nss if int(ns) in self.spill]
        out: Dict[int, Tuple[np.ndarray, ...]] = {}
        if resident:
            keys = np.full(len(resident), key_id, dtype=np.int64)
            slots = self.index.lookup(
                keys, np.asarray(resident, dtype=np.int64))
            hit = slots >= 0
            if hit.any():
                hs = slots[hit].astype(np.int32)
                size = pad_bucket_size(len(hs), minimum=64)
                gathered = self.agg._gather_jit(
                    self.accs, jnp.asarray(pad_i32(hs, size, fill=0)))
                leaves = [g[:len(hs)] for g in jax.device_get(gathered)]
                for j, ns in enumerate(n for n, h in zip(resident, hit)
                                       if h):
                    out[int(ns)] = tuple(l[j:j + 1] for l in leaves)
        for ns in spilled:
            if self._paged:
                # session id -> its page (read-only: queries must not
                # change residency)
                page = self._pmap.page_of(int(ns))
                entry = self.spill.peek(page) if page is not None else None
                if entry is None:
                    continue
                pos = np.nonzero(
                    (np.asarray(entry["key_id"], dtype=np.int64)
                     == key_id)
                    & (np.asarray(entry["ns"], dtype=np.int64)
                       == int(ns)))[0]
            else:
                entry = self.spill.peek(int(ns))
                if entry is None:
                    continue
                pos = np.nonzero(np.asarray(entry["key_id"],
                                            dtype=np.int64) == key_id)[0]
            if len(pos) == 0:
                continue
            j = int(pos[0])
            out[int(ns)] = tuple(
                np.asarray(entry[f"leaf_{i}"], dtype=l.dtype)[j:j + 1]
                for i, l in enumerate(self.agg.leaves))
        return out

    def query_batch_pairs(
            self, key_ids: np.ndarray, namespaces: np.ndarray
    ) -> Tuple[np.ndarray, List[np.ndarray]]:
        """Raw accumulator leaves for N ``(key, namespace)`` pairs — the
        serving-plane primitive: device-resident pairs read through ONE
        gather kernel + ONE batched device read for the whole batch
        (per-pair reads pay one link round-trip each — the TRC01 class),
        spilled pairs from their host tiers. Returns ``(found, leaves)``
        where ``found`` is the per-pair hit mask and ``leaves`` are
        [N]-shaped per-leaf value arrays (identity where not found).
        Read-only: no residency change, no sticky-bucket mutation."""
        key_ids = np.asarray(key_ids, dtype=np.int64)
        namespaces = np.asarray(namespaces, dtype=np.int64)
        n = len(key_ids)
        leaves_out = [np.full(n, l.identity, dtype=l.dtype)
                      for l in self.agg.leaves]
        found = np.zeros(n, dtype=bool)
        if n == 0:
            return found, leaves_out
        slots = self.index.lookup(key_ids, namespaces)
        hit = slots >= 0
        if hit.any():
            hs = slots[hit].astype(np.int32)
            size = pad_bucket_size(len(hs), minimum=64)
            gathered = self.agg._gather_jit(
                self.accs, jnp.asarray(pad_i32(hs, size, fill=0)))
            g_host = jax.device_get(gathered)  # ONE batched D2H
            for i, g in enumerate(g_host):
                leaves_out[i][hit] = g[:int(hit.sum())]
            found |= hit
        miss = np.nonzero(~hit)[0]
        if len(miss) and (self._paged or len(self.spill)):
            from flink_tpu.state.paged_spill import read_spilled_rows

            def _take_row(j, entry, src):
                for i, l in enumerate(self.agg.leaves):
                    leaves_out[i][j] = np.asarray(
                        entry[f"leaf_{i}"], dtype=l.dtype)[src]
                found[j] = True

            read_spilled_rows(
                self.spill, self._pmap if self._paged else None,
                self._paged,
                [(j, int(key_ids[j]), int(namespaces[j]))
                 for j in miss.tolist()],
                _take_row)
        return found, leaves_out

    def query_windows(self, key_id: int, assigner
                      ) -> Dict[int, Dict[str, float]]:
        """Point lookup composing WINDOW results from per-slice partial
        accumulators (slice sharing: a sliding window's value = merge of k
        slices — reference: SliceAssigners slice/window mapping). Returns
        {window_end -> finished result columns} for the key. Read-only."""
        from flink_tpu.ops.segment_ops import HOST_COMBINE

        live_ns = [int(n) for n in self.namespaces]
        if not live_ns:
            return {}
        slice_vals = self._key_values_per_namespace(int(key_id), live_ns)
        if not slice_vals:
            return {}
        windows = sorted({
            int(w)
            for se in slice_vals
            for w in assigner.window_ends_for_slice(se)})
        out: Dict[int, Dict[str, float]] = {}
        for w in windows:
            leaves = [np.full(1, l.identity, dtype=l.dtype)
                      for l in self.agg.leaves]
            for se in assigner.slice_ends_for_window(w):
                sv = slice_vals.get(int(se))
                if sv is None:
                    continue
                leaves = [HOST_COMBINE[l.reduce](acc, v) for acc, v, l in
                          zip(leaves, sv, self.agg.leaves)]
            finished = self.agg.finish(tuple(leaves))
            out[w] = {name: np.asarray(col).item()
                      for name, col in finished.items()}
        return out

    # ---------------------------------------------------------- snapshot/restore

    def snapshot(self, reset_dirty: bool = True) -> Dict[str, np.ndarray]:
        """Materialize state as host arrays, filtered to used slots.

        The snapshot is *logical* (key, ns, key_group, leaf values) — slot
        numbers are not part of the format, so restore can re-shard by key
        group (the reference's rescale-by-key-group-range contract,
        reference: KeyGroupRangeAssignment.java + state/restore pipeline).
        With ``reset_dirty`` (the default) the snapshot establishes a new
        incremental base; savepoints pass False so a mid-run savepoint does
        not silently shrink the next delta checkpoint's contents.
        """
        used = self.index.used_slots()
        accs_host = jax.device_get(list(self.accs))  # ONE batched D2H
        key_ids = self.index.slot_key[used]
        out = {
            "key_id": key_ids,
            "namespace": self.index.slot_ns[used],
            **{
                f"leaf_{i}": accs_host[i][used]
                for i in range(len(self.accs))
            },
        }
        # spilled namespaces are part of the logical state (chunks
        # collected first, ONE concatenate — thousands of one-row session
        # namespaces would otherwise make this O(N^2))
        key_chunks = [out["key_id"]]
        ns_chunks = [out["namespace"]]
        leaf_chunks = [[out[f"leaf_{i}"]] for i in range(len(self.accs))]
        for pid_or_ns in self.spill.namespaces:
            entry = self.spill.peek(int(pid_or_ns))
            keys = np.asarray(entry["key_id"], dtype=np.int64)
            if "ns" in entry:  # paged layout: entry carries its ns column
                rns = np.asarray(entry["ns"], dtype=np.int64)
                # lazy tombstones: reloaded/freed rows stay physically
                # in the page; only rows still MAPPED to it are state
                alive = self._pmap.live_row_mask(int(pid_or_ns), rns)
                keys, rns = keys[alive], rns[alive]
                sel = alive
            else:
                rns = np.full(len(keys), int(pid_or_ns), dtype=np.int64)
                sel = slice(None)
            key_chunks.append(keys)
            ns_chunks.append(rns)
            for i in range(len(self.accs)):
                leaf_chunks[i].append(
                    np.asarray(entry[f"leaf_{i}"],
                               dtype=self.agg.leaves[i].dtype)[sel])
        out["key_id"] = np.concatenate(key_chunks)
        out["namespace"] = np.concatenate(ns_chunks)
        for i in range(len(self.accs)):
            out[f"leaf_{i}"] = np.concatenate(leaf_chunks[i])
        out["key_group"] = assign_key_groups(out["key_id"],
                                             self.max_parallelism)
        if reset_dirty:
            self._dirty[:] = False
            self._freed_ns.clear()
            self._freed_pairs.clear()
            self.spill.clear_dirty()
        return out

    def snapshot_delta(self) -> Dict[str, np.ndarray]:
        """Incremental snapshot: only rows dirtied since the last snapshot
        plus the namespaces freed since (tombstones). Restore applies deltas
        on top of the last full snapshot
        (reference: RocksIncrementalSnapshotStrategy — upload only new SSTs;
        here: transfer only dirty slots off the device)."""
        dirty_used = np.nonzero(self._dirty & self.index.slot_used)[0] \
            .astype(np.int32)
        freed = np.asarray(sorted(set(self._freed_ns)), dtype=np.int64)
        n = len(dirty_used)
        if n:
            size = sticky_bucket(n, self._gather_bucket)
            self._gather_bucket = size
            gathered = self.agg._gather_jit(
                self.accs, jnp.asarray(pad_i32(dirty_used, size, fill=0)))
            leaves = [g[:n] for g in jax.device_get(gathered)]
        else:
            leaves = [np.empty(0, dtype=l.dtype) for l in self.agg.leaves]
        key_ids = self.index.slot_key[dirty_used]
        namespaces = self.index.slot_ns[dirty_used]
        # spilled-but-dirty namespaces were changed since the last snapshot
        # and must travel in this delta too (paged layout: only the dirty
        # ROWS of a dirty page — pages are immutable once spilled, so the
        # per-row dirty column captured at eviction stays authoritative)
        for pid_or_ns in self.spill.dirty_namespaces():
            entry = self.spill.peek(int(pid_or_ns))
            if entry is None:
                continue
            keys = np.asarray(entry["key_id"], dtype=np.int64)
            if "ns" in entry:
                rns_all = np.asarray(entry["ns"], dtype=np.int64)
                # dirty rows that are also LIVE (tombstoned rows are
                # either resident again — the resident copy travels —
                # or freed, so their stale page copy must not)
                sel = (np.asarray(entry["dirty"], dtype=bool)
                       & self._pmap.live_row_mask(int(pid_or_ns),
                                                  rns_all))
                keys = keys[sel]
                rns = rns_all[sel]
            else:
                sel = slice(None)
                rns = np.full(len(keys), int(pid_or_ns), dtype=np.int64)
            key_ids = np.concatenate([key_ids, keys])
            namespaces = np.concatenate([namespaces, rns])
            leaves = [np.concatenate([
                leaves[i],
                np.asarray(entry[f"leaf_{i}"],
                           dtype=self.agg.leaves[i].dtype)[sel]])
                for i in range(len(leaves))]
        if self._freed_pairs:
            tomb_k = np.concatenate([p[0] for p in self._freed_pairs])
            tomb_n = np.concatenate([p[1] for p in self._freed_pairs])
        else:
            tomb_k = np.empty(0, dtype=np.int64)
            tomb_n = np.empty(0, dtype=np.int64)
        out = {
            "__delta__": np.asarray(True),
            "key_id": key_ids,
            "namespace": namespaces,
            "key_group": assign_key_groups(key_ids, self.max_parallelism),
            "freed_namespaces": freed,
            "tombstone_key_id": tomb_k,
            "tombstone_namespace": tomb_n,
            **{f"leaf_{i}": leaves[i] for i in range(len(leaves))},
        }
        self._dirty[:] = False
        self._freed_ns.clear()
        self._freed_pairs.clear()
        self.spill.clear_dirty()
        return out

    def restore(self, snap: Dict[str, np.ndarray],
                key_group_filter=None) -> None:
        """Load a logical snapshot, optionally keeping only owned key groups."""
        key_ids = np.asarray(snap["key_id"], dtype=np.int64)
        namespaces = np.asarray(snap["namespace"], dtype=np.int64)
        groups = np.asarray(snap["key_group"], dtype=np.int32)
        leaves = [np.asarray(snap[f"leaf_{i}"]) for i in range(len(self.agg.leaves))]
        # serializer-compatibility check (reference: TypeSerializerSnapshot
        # resolveSchemaCompatibility): leaf dtypes must match the
        # aggregate's accumulator layout. A value-preserving cast counts as
        # compatible-after-migration (bootstrap writers use natural Python
        # dtypes); anything lossy fails precisely instead of silently
        # reinterpreting values.
        for i, (arr, leaf) in enumerate(zip(leaves, self.agg.leaves)):
            want = np.dtype(leaf.dtype)
            if len(arr) and arr.dtype != want:
                cast = _coerce_snapshot_leaf(arr, want)
                if cast is None:
                    raise RuntimeError(
                        f"state schema incompatible: snapshot leaf_{i} has "
                        f"dtype {arr.dtype}, the aggregate expects {want} "
                        "and the values do not survive the cast — migrate "
                        "the snapshot (checkpoint.storage."
                        "register_migration) or restore with the original "
                        "aggregate types")
                leaves[i] = cast
        if key_group_filter is not None:
            mask = np.array([g in key_group_filter for g in groups], dtype=bool)
            key_ids, namespaces = key_ids[mask], namespaces[mask]
            leaves = [l[mask] for l in leaves]
        if self.max_device_slots and self._paged and len(key_ids):
            # paged restore: rows land in page-sized spill entries (ns
            # column per row) and reload lazily by page — same bounded-
            # device contract, thousands of sessions per entry
            from flink_tpu.state.paged_spill import restore_into_pages

            restore_into_pages(
                self.spill, self._pmap, key_ids, namespaces, leaves,
                page_rows=max(self.index.capacity // 8, 1024))
        elif self.max_device_slots and len(key_ids):
            # spill-enabled restore: rows land in the spill tier grouped by
            # namespace and reload lazily on first access — a snapshot far
            # larger than HBM restores with bounded device memory
            order = np.argsort(namespaces, kind="stable")
            s_ns = namespaces[order]
            s_keys = key_ids[order]
            s_leaves = [l[order] for l in leaves]
            bounds = np.nonzero(np.diff(s_ns))[0] + 1
            starts = np.concatenate(([0], bounds))
            ends = np.concatenate((bounds, [len(s_ns)]))
            for a, b in zip(starts.tolist(), ends.tolist()):
                ns = int(s_ns[a])
                entry = {"key_id": s_keys[a:b],
                         **{f"leaf_{i}": s_leaves[i][a:b]
                            for i in range(len(s_leaves))}}
                if ns in self.spill:
                    self.spill.drop(ns)
                self.spill.put(ns, entry, dirty=False)
                # the namespace registry must know spilled namespaces'
                # windows; registry entries are created on reload
        elif len(key_ids):
            slots = self.lookup_or_insert(key_ids, namespaces)
            # one batched D2H read, then writable copies (mutated below)
            accs_host = [np.array(a)
                         for a in jax.device_get(list(self.accs))]
            for acc, vals in zip(accs_host, leaves):
                acc[slots] = vals
            self.accs = tuple(jnp.asarray(a) for a in accs_host)
        # restored state IS the new incremental base
        self._dirty[:] = False
        self._freed_ns.clear()
        self.spill.clear_dirty()
