"""State time-to-live — expire idle keyed state.

reference: flink-core/src/main/java/org/apache/flink/api/common/state/
StateTtlConfig.java:1 (builder with UpdateType OnCreateAndWrite /
OnReadAndWrite, StateVisibility NeverReturnExpired /
ReturnExpiredIfNotCleanedUp, processing-time characteristic) and
flink-runtime/src/main/java/org/apache/flink/runtime/state/ttl/
TtlStateFactory.java:1 (wraps every state kind with a
last-access-timestamped value and filters expired reads).

Re-design for a columnar engine: instead of wrapping each value with a
``TtlValue<T>`` object carrying its own timestamp (the reference's
per-entry serialization change), TTL is a **last-access int64 column per
state** — one stamp per slot next to the dense value arrays. Reads and
sweeps are then vectorized mask operations over the whole table
(``now - stamps > ttl``), which is both cheaper than per-entry
timestamps and snapshot-compatible (stamps travel as one more column).
The cleanup analog of the reference's full-snapshot / incremental /
compaction-filter strategies is a single vectorized sweep run on
watermark or processing-time advance.

Time characteristic is PROCESSING time, like the reference (event-time
TTL was never shipped there; StateTtlConfig.TtlTimeCharacteristic has
only ProcessingTime).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from flink_tpu.core.annotations import public

#: UpdateType — which accesses refresh the entry's lifetime
ON_CREATE_AND_WRITE = "OnCreateAndWrite"
ON_READ_AND_WRITE = "OnReadAndWrite"

#: StateVisibility — what an expired-but-not-yet-swept read returns
NEVER_RETURN_EXPIRED = "NeverReturnExpired"
RETURN_EXPIRED_IF_NOT_CLEANED_UP = "ReturnExpiredIfNotCleanedUp"


def default_clock() -> int:
    """Processing-time now, epoch millis."""
    return int(time.time() * 1000)


@public
@dataclasses.dataclass(frozen=True)
class StateTtlConfig:
    """TTL policy for one state (reference: StateTtlConfig builder).

    ``ttl_ms``        — entry lifetime since its last qualifying access.
    ``update_type``   — ON_CREATE_AND_WRITE (writes refresh; default) or
                        ON_READ_AND_WRITE (reads refresh too).
    ``visibility``    — NEVER_RETURN_EXPIRED (default; an expired entry
                        reads as absent even before cleanup) or
                        RETURN_EXPIRED_IF_NOT_CLEANED_UP.
    """

    ttl_ms: int
    update_type: str = ON_CREATE_AND_WRITE
    visibility: str = NEVER_RETURN_EXPIRED

    def __post_init__(self):
        if self.ttl_ms <= 0:
            raise ValueError("ttl_ms must be positive")
        if self.update_type not in (ON_CREATE_AND_WRITE,
                                    ON_READ_AND_WRITE):
            raise ValueError(f"unknown update_type {self.update_type!r}")
        if self.visibility not in (NEVER_RETURN_EXPIRED,
                                   RETURN_EXPIRED_IF_NOT_CLEANED_UP):
            raise ValueError(f"unknown visibility {self.visibility!r}")

    @staticmethod
    def new_builder(ttl_ms: int) -> "TtlConfigBuilder":
        return TtlConfigBuilder(ttl_ms)


class TtlConfigBuilder:
    """Fluent construction mirroring the reference's builder API."""

    def __init__(self, ttl_ms: int):
        self._ttl_ms = ttl_ms
        self._update = ON_CREATE_AND_WRITE
        self._visibility = NEVER_RETURN_EXPIRED

    def set_update_type(self, update_type: str) -> "TtlConfigBuilder":
        self._update = update_type
        return self

    def update_ttl_on_read_and_write(self) -> "TtlConfigBuilder":
        self._update = ON_READ_AND_WRITE
        return self

    def set_state_visibility(self, visibility: str) -> "TtlConfigBuilder":
        self._visibility = visibility
        return self

    def return_expired_if_not_cleaned_up(self) -> "TtlConfigBuilder":
        self._visibility = RETURN_EXPIRED_IF_NOT_CLEANED_UP
        return self

    def build(self) -> StateTtlConfig:
        return StateTtlConfig(self._ttl_ms, self._update, self._visibility)


#: stamp value meaning "no entry" (never written / swept away)
NO_STAMP = np.int64(-1)


class SweepGate:
    """Shared cadence for interval-gated TTL sweeps: fire at most every
    ttl/4 (floor 1 ms) so the vectorized scan amortizes across batches.
    Used by every operator that sweeps (GroupAgg, upsert materializer)."""

    def __init__(self, ttl_ms: int):
        self.ttl_ms = ttl_ms
        self._last = 0

    def should_sweep(self, now_ms: int) -> bool:
        if now_ms - self._last < max(self.ttl_ms // 4, 1):
            return False
        self._last = now_ms
        return True


class TtlStamps:
    """Per-slot last-access column for one dense state.

    Vectorized counterpart of the reference's TtlValue timestamps: one
    int64 per slot, ``NO_STAMP`` where the entry is absent."""

    def __init__(self, capacity: int, cfg: StateTtlConfig):
        self.cfg = cfg
        self.stamps = np.full(capacity, NO_STAMP, dtype=np.int64)

    def grow(self, old: int, new: int) -> None:
        grown = np.full(new, NO_STAMP, dtype=np.int64)
        grown[:old] = self.stamps
        self.stamps = grown

    def touch(self, slots: np.ndarray, now_ms: int) -> None:
        self.stamps[slots] = now_ms

    def touch_on_read(self, slots: np.ndarray, now_ms: int) -> None:
        if self.cfg.update_type == ON_READ_AND_WRITE:
            # only refresh entries that still exist and are not expired
            # (reading an expired entry must not resurrect it)
            s = self.stamps[slots]
            live = (s != NO_STAMP) & (now_ms - s <= self.cfg.ttl_ms)
            self.stamps[slots[live]] = now_ms

    def expired_mask(self, slots: np.ndarray, now_ms: int) -> np.ndarray:
        """True where the entry exists but its lifetime has passed."""
        s = self.stamps[slots]
        return (s != NO_STAMP) & (now_ms - s > self.cfg.ttl_ms)

    def hidden_mask(self, slots: np.ndarray, now_ms: int) -> np.ndarray:
        """True where a READ must pretend the entry is absent."""
        if self.cfg.visibility == RETURN_EXPIRED_IF_NOT_CLEANED_UP:
            return np.zeros(len(slots), dtype=bool)
        return self.expired_mask(slots, now_ms)

    def sweep(self, now_ms: int) -> np.ndarray:
        """All expired slots (for cleanup); resets their stamps."""
        expired = np.nonzero(
            (self.stamps != NO_STAMP)
            & (now_ms - self.stamps > self.cfg.ttl_ms))[0]
        self.stamps[expired] = NO_STAMP
        return expired

    def clear(self, slots: np.ndarray) -> None:
        self.stamps[slots] = NO_STAMP

    def snapshot(self) -> np.ndarray:
        return self.stamps.copy()

    def restore(self, snap: np.ndarray, slot_remap=None) -> None:
        snap = np.asarray(snap, dtype=np.int64)
        if slot_remap is not None:
            self.stamps[slot_remap[1]] = snap[slot_remap[0]]
        else:
            self.stamps[: len(snap)] = snap
