"""Pane-layout keyed window state: a ring of slices × stable key rows.

The SlotTable (state/slot_table.py) allocates one slot per (key, slice)
pair, so firing a k-slice window needs a host-built [num_keys, k] slot
matrix shipped host->device every fire — on a transfer-constrained TPU
link that dominates the fire cost. This layout removes the matrix
entirely (reference analog: the pane/slice-sharing idea of
SliceAssigners.java taken to its natural TPU form):

- device arrays are ``[ring_rows, key_capacity]`` per accumulator leaf;
- a KEY owns a stable column (key row) across all slices (host index,
  keyed by key only);
- a live SLICE owns a ring row (host dict slice_end -> row; row 0 is the
  reserved always-identity row, the pad target for missing slices);
- scatter: ``acc[row[i], col[i]] op= v[i]`` — same host->device traffic
  as the slot layout (indices + values);
- FIRE: ``merge(acc[rows_of_window], axis=0)`` + finish (+ fused top-k
  projector) — the only host->device transfer is the [k] ring-row ids;
- freeing an expired slice is ONE index-free row reset;
- the incremental-snapshot unit is a slice row, and sealed slices never
  dirty again — a delta checkpoint ships just the active slice.

A presence plane (int8 max-scatter) distinguishes "key has data in this
slice" from identity values, so fires emit exactly the keys that
participated (SUM of 0.0 is not confused with absence).

Scope: aligned (non-merging) assigners on one device without a spill
tier; sessions, spill, and the mesh keep the slot layout.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.core.annotations import internal
from flink_tpu.ops.segment_ops import (
    pad_i32,
    sticky_bucket,
)
from flink_tpu.state.keygroups import assign_key_groups
from flink_tpu.state.slot_table import make_slot_index
from flink_tpu.stateplane import pane_programs
from flink_tpu.stateplane.families import pane_fence
from flink_tpu.windowing.aggregates import AggregateFunction

_INITIAL_RING = 8


def _pane_kernels(agg: AggregateFunction, projector=None):
    """(scatter2d, scatter2d_valued, fire_rows, reset_row, put_row,
    fold_rows) for [R, C] pane arrays — the stateplane delta-harvest
    bundle (bodies in ``flink_tpu/stateplane/pane.py``). The presence
    plane rides as an extra trailing array in ``accs``."""
    return pane_programs(agg, projector)


@internal
class PaneTable:
    """Ring-of-slices × key-rows window state (see module docstring)."""

    def __init__(self, agg: AggregateFunction, capacity: int = 1 << 16,
                 max_parallelism: int = 128, fire_projector=None,
                 memory=None, slices_for_window=None):
        self.agg = agg
        self.max_parallelism = max_parallelism
        self.fire_projector = fire_projector
        #: window_end -> slice ends (the assigner's mapping) — needed to
        #: rebuild window-partial rows from the authoritative panes
        #: after a restore or an internal compaction (preagg mode)
        self._slices_for_window = slices_for_window
        #: (MemoryManager, owner) — the DENSE [R, capacity] per-leaf
        #: footprint (plus the int8 presence plane) is managed
        #: (flink_tpu/core/memory.py), the layout most likely to exhaust
        #: HBM on high-ratio sliding windows
        self._memory = memory
        self.index = make_slot_index(capacity, on_grow=self._grow_cols)
        self.capacity = self.index.capacity
        self.R = _INITIAL_RING
        self._reserve_cells(self.R * self.capacity)
        self.accs = tuple(
            jnp.full((self.R, self.capacity), l.identity, dtype=l.dtype)
            for l in agg.leaves
        ) + (jnp.zeros((self.R, self.capacity), dtype=jnp.int8),)
        #: slice_end -> ring row (row 0 reserved identity)
        self.slice_row: Dict[int, int] = {}
        #: window_end -> ring row holding the window's RUNNING PARTIAL
        #: (incremental pane pre-aggregation: combined at absorb so a
        #: fire gathers exactly the one pane that closes). Derived
        #: state — snapshots ignore it, restore/compaction rebuild it
        #: from the panes.
        self.window_row: Dict[int, int] = {}
        self._free_rows: List[int] = list(range(self.R - 1, 0, -1))
        self._dirty_slices: set = set()
        self._freed_ns: List[int] = []
        self._scatter_bucket = 0
        #: exclusive bound of allocated key rows (keys are never freed, so
        #: allocations stay contiguous from 1)
        self._high_water = 1
        (self._scatter2d, self._scatter2d_valued, self._fire_rows,
         self._reset_row, self._put_row,
         self._fold_rows) = _pane_kernels(agg, fire_projector)

    # ---------------------------------------------------------------- sizing

    def _cell_bytes(self) -> int:
        return sum(np.dtype(l.dtype).itemsize
                   for l in self.agg.leaves) + 1  # + presence plane

    def _reserve_cells(self, cells: int) -> None:
        if self._memory is not None:
            manager, owner = self._memory
            manager.reserve(owner, cells * self._cell_bytes())

    def release_memory(self) -> None:
        if self._memory is not None:
            manager, owner = self._memory
            manager.release_all(owner)

    def _grow_cols(self, old: int, new: int) -> None:
        self._reserve_cells(self.R * (new - old))
        self.capacity = new
        grown = []
        for a, l in zip(self.accs[:-1], self.agg.leaves):
            pad = jnp.full((self.R, new - old), l.identity, dtype=l.dtype)
            grown.append(jnp.concatenate([a, pad], axis=1))
        pad = jnp.zeros((self.R, new - old), dtype=jnp.int8)
        self.accs = tuple(grown) + (
            jnp.concatenate([self.accs[-1], pad], axis=1),)

    def _take_row(self) -> int:
        if not self._free_rows:
            old = self.R
            self._reserve_cells(old * self.capacity)  # doubling the ring
            self.R = old * 2
            grown = []
            for a, l in zip(self.accs[:-1], self.agg.leaves):
                pad = jnp.full((old, self.capacity), l.identity,
                               dtype=l.dtype)
                grown.append(jnp.concatenate([a, pad], axis=0))
            pad = jnp.zeros((old, self.capacity), dtype=jnp.int8)
            self.accs = tuple(grown) + (
                jnp.concatenate([self.accs[-1], pad], axis=0),)
            self._free_rows = list(range(self.R - 1, old - 1, -1))
        return self._free_rows.pop()

    def _alloc_row(self, slice_end: int) -> int:
        row = self._take_row()
        self.slice_row[int(slice_end)] = row
        return row

    def _alloc_window_row(self, window_end: int) -> int:
        row = self._take_row()
        self.window_row[int(window_end)] = row
        return row

    @property
    def used_cols(self) -> int:
        """High-water key-row bound (exclusive); row 0 is reserved."""
        return self._high_water

    # ---------------------------------------------------------------- ingest

    #: upsert()/upsert_valued() take a precomputed ``slice_plan``
    #: (uniq, inverse) from WindowAssigner.slice_plan — saves a full
    #: sort of the batch (see SliceSharedWindower.process_batch)
    accepts_slice_plan = True

    def _flat_indices(self, key_ids: np.ndarray,
                      slice_ends: np.ndarray,
                      slice_plan=None) -> np.ndarray:
        """[n] fused (ring row, key col) -> flat i32 scatter indices — one
        index array over the host->device link instead of two (fill 0 =
        reserved identity row 0 / col 0)."""
        cols = self.index.lookup_or_insert(
            key_ids, np.zeros(len(key_ids), dtype=np.int64))
        if len(cols):
            self._high_water = max(self._high_water, int(cols.max()) + 1)
        # slice -> ring row: rows for the (few) unique slices via the host
        # dict, broadcast back per record with the unique-inverse (no
        # Python-level per-record loop)
        uniq, inv = slice_plan if slice_plan is not None else \
            np.unique(slice_ends, return_inverse=True)
        for se in uniq.tolist():
            if int(se) not in self.slice_row:
                self._alloc_row(int(se))
            self._dirty_slices.add(int(se))
        uniq_rows = np.fromiter(
            (self.slice_row[int(se)] for se in uniq.tolist()),
            dtype=np.int64, count=len(uniq))
        rows = uniq_rows[inv]
        self._check_flat_range()
        return (rows * self.capacity + cols).astype(np.int32)

    def ingest_indices(self, key_ids: np.ndarray, timestamps: np.ndarray,
                       offset: int, width: int):
        """Fused index build: ONE native sweep (sm_pane_ingest) replaces
        assign_slice_ends + slice_plan + lookup_or_insert + the flat
        fuse — the five memory-bound numpy passes that dominated ingest
        on large micro-batches. Returns (flat, uniq_ends, sinv) or None
        when the native library is absent or the batch has pathologically
        many distinct slice ends (callers fall back to the numpy path)."""
        ingest = getattr(self.index, "pane_ingest", None)
        if ingest is None:
            return None
        res = ingest(key_ids, timestamps, offset, width)
        if res is None:
            return None
        cols, sinv, uniq, max_col = res
        self._high_water = max(self._high_water, max_col + 1)
        rowmap = np.empty(len(uniq), dtype=np.int64)
        for j, se in enumerate(uniq.tolist()):
            se = int(se)
            if se not in self.slice_row:
                self._alloc_row(se)
            self._dirty_slices.add(se)
            rowmap[j] = self.slice_row[se]
        self._check_flat_range()
        flat = self.index.flat_fuse(cols, sinv, rowmap, self.capacity)
        return flat, uniq, sinv

    def scatter_flat(self, flat: np.ndarray,
                     values: Tuple[np.ndarray, ...],
                     valued: bool = False) -> None:
        """Scatter with a prebuilt flat index (see ingest_indices)."""
        size = sticky_bucket(len(flat), self._scatter_bucket)
        self._scatter_bucket = size
        if valued:
            from flink_tpu.ops.segment_ops import pad_values

            self.accs = self._scatter2d_valued(
                self.accs, pad_i32(flat, size, fill=0),
                tuple(pad_values(np.asarray(v, dtype=l.dtype), size,
                                 l.identity)
                      for v, l in zip(values, self.agg.leaves)))
        else:
            self.accs = self._scatter2d(
                self.accs, pad_i32(flat, size, fill=0),
                self.agg.pad_input_values(values, size))

    def upsert(self, key_ids: np.ndarray, slice_ends: np.ndarray,
               values: Tuple[np.ndarray, ...], slice_plan=None) -> None:
        flat = self._flat_indices(key_ids, slice_ends, slice_plan)
        size = sticky_bucket(len(flat), self._scatter_bucket)
        self._scatter_bucket = size
        self.accs = self._scatter2d(
            self.accs,
            pad_i32(flat, size, fill=0),
            self.agg.pad_input_values(values, size))

    def upsert_valued(self, key_ids: np.ndarray, slice_ends: np.ndarray,
                      values: Tuple[np.ndarray, ...],
                      slice_plan=None) -> None:
        """Fold locally pre-aggregated partials (every leaf valued; see
        flink_tpu.runtime.local_agg)."""
        from flink_tpu.ops.segment_ops import pad_values

        flat = self._flat_indices(key_ids, slice_ends, slice_plan)
        size = sticky_bucket(len(flat), self._scatter_bucket)
        self._scatter_bucket = size
        self.accs = self._scatter2d_valued(
            self.accs,
            pad_i32(flat, size, fill=0),
            tuple(pad_values(np.asarray(v, dtype=l.dtype), size, l.identity)
                  for v, l in zip(values, self.agg.leaves)))

    # ------------------------------------- incremental pane pre-aggregation

    def has_window_partial(self, window_end: int) -> bool:
        return int(window_end) in self.window_row

    def _check_flat_range(self) -> None:
        if self.R * self.capacity > np.iinfo(np.int32).max:
            raise RuntimeError(
                f"pane table exceeds int32 flat-index range "
                f"(ring={self.R} x capacity={self.capacity}); lower "
                "state.slot-table.capacity or the window's slice count")

    def window_flat(self, cols: np.ndarray, sinv: np.ndarray,
                    wins_per_slice):
        """Flat scatter indices folding each record into its live
        windows' PARTIAL rows (combine-on-absorb). ``cols`` are the
        records' key columns (``flat %% capacity``), ``sinv`` the
        unique-slice inverse, ``wins_per_slice`` one list of window
        ends per unique slice — only windows that already HAVE a
        partial row receive direct folds (missing ones are rebuilt
        from the authoritative panes after the scatter). Returns
        ``(flat, rec_idx)`` or None when nothing folds."""
        chunks_f: List[np.ndarray] = []
        chunks_i: List[np.ndarray] = []
        order = np.argsort(sinv, kind="stable")
        counts = np.bincount(sinv, minlength=len(wins_per_slice))
        offs = np.concatenate(([0], np.cumsum(counts)))
        C = self.capacity
        self._check_flat_range()
        for j, wins in enumerate(wins_per_slice):
            if not wins:
                continue
            sel = order[offs[j]:offs[j + 1]]
            if not len(sel):
                continue
            c = cols[sel].astype(np.int64)
            for w in wins:
                row = self.window_row.get(int(w))
                if row is None:
                    continue
                # pad lanes (col 0) stay on the identity column of the
                # window row: (flat %% C) == 0 keeps them pure
                chunks_f.append((row * C + c).astype(np.int32))
                chunks_i.append(sel)
        if not chunks_f:
            return None
        return np.concatenate(chunks_f), np.concatenate(chunks_i)

    def scatter_combined(self, flat: np.ndarray, win,
                         values: Tuple[np.ndarray, ...],
                         valued: bool = False) -> None:
        """One scatter covering the pane cells AND the window-partial
        cells: the window half replicates each record's value through
        ``rec_idx`` (see window_flat), so the whole batch still costs
        ONE flat index array over the link and ONE dispatch."""
        if win is None:
            return self.scatter_flat(flat, values, valued)
        flat_w, rec_idx = win
        flat_all = np.concatenate([flat, flat_w])
        vals = tuple(np.concatenate([np.asarray(v), np.asarray(v)[rec_idx]])
                     for v in values)
        self.scatter_flat(flat_all, vals, valued)

    def rebuild_window_partials(self, window_ends) -> int:
        """(Re)build partial rows for pending windows that lack one —
        fold of the window's pane rows (the panes are authoritative:
        this is exactly the full-window harvest, landed into a ring row
        instead of the host). Runs after restore, after compaction, and
        for windows newly pending this batch (including late
        re-registrations under allowed lateness). Returns rows built."""
        if self._slices_for_window is None:
            return 0
        built = 0
        # sorted: ring-row allocation order must be deterministic
        # (window_ends may arrive as a set)
        for w in sorted(int(x) for x in window_ends):
            if w in self.window_row:
                continue
            rows = [self.slice_row.get(int(se), 0)
                    for se in self._slices_for_window(w)]
            if not any(rows):
                continue  # no pane data: the fire falls back / emits nothing
            dst = self._alloc_window_row(w)
            self.accs = self._fold_rows(
                self.accs, dst,
                jnp.asarray(np.asarray(rows, dtype=np.int32)))
            built += 1
        return built

    def release_window_row(self, window_end: int) -> None:
        """Reset + free a fired window's partial row (queue-ordered
        behind the fire kernel, so deferred harvests never race it)."""
        row = self.window_row.pop(int(window_end), None)
        if row is None:
            return
        self.accs = self._reset_row(self.accs, row)
        self._free_rows.append(row)

    def clear_window_rows(self) -> None:
        for w in list(self.window_row):
            self.release_window_row(w)

    def fire_partial(self, window_end: int
                     ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Delta fire: gather ONE partial ring row — the pane that
        closes — instead of merging the window's k slice rows. The row
        is released after the fire (a fired window's partial is spent;
        a late re-registration rebuilds it from the retained panes)."""
        row = self.window_row.get(int(window_end))
        if row is None:
            return np.empty(0, dtype=np.int64), {}
        out = self._harvest_rows(np.asarray([row], dtype=np.int32))
        self.release_window_row(window_end)
        return out

    def fire_partial_async(self, window_end: int):
        """Async delta fire: PendingFire (or None) whose harvest yields
        (keys, result columns); the row release is dispatched right
        after the fire kernel (device-queue-ordered behind it)."""
        row = self.window_row.get(int(window_end))
        if row is None:
            return None
        pf = self._harvest_rows_async(np.asarray([row], dtype=np.int32))
        self.release_window_row(window_end)
        return pf

    def make_fence(self):
        """Dispatch-depth fence (see SlotTable.make_fence): a [1, 1] slice
        of the live accumulator, enqueued behind all prior work."""
        return pane_fence(self.agg.leaves[0].dtype.str)(self.accs[0])

    # ------------------------------------------------------------------ fire

    def fire_window(self, slice_ends: List[int]
                    ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """(keys, result columns) for one window — missing slices hit the
        reserved identity row; the ONLY host->device payload is [k] row
        ids."""
        rows = np.asarray(
            [self.slice_row.get(int(se), 0) for se in slice_ends],
            dtype=np.int32)
        if not rows.any():
            return np.empty(0, dtype=np.int64), {}
        return self._harvest_rows(rows)

    def fire_window_async(self, slice_ends: List[int]):
        """Async-dispatch variant of fire_window: returns a PendingFire
        (or None for a no-op window) whose harvest yields (keys, result
        columns)."""
        rows = np.asarray(
            [self.slice_row.get(int(se), 0) for se in slice_ends],
            dtype=np.int32)
        if not rows.any():
            return None
        return self._harvest_rows_async(rows)

    def _harvest_rows(self, rows: np.ndarray
                      ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Merge+finish the given ring rows and materialize (keys,
        result columns) — THE one sync harvest body, shared by the
        full-window fire (k slice rows) and the delta fire (one partial
        row), so projector/harvest semantics cannot drift between the
        two paths. One batched device_get: each independent read costs
        a full link RTT, batched reads pipeline into ~one."""
        used = self.used_cols
        out = self._fire_rows(self.accs, jnp.asarray(rows), used)
        if self.fire_projector is None:
            cols, valid = out
            names = list(cols)
            host = jax.device_get([valid] + [cols[n] for n in names])
            sel = host[0][:used]
            keys = self.index.slot_key[:used][sel]
            return keys, {name: c[:used][sel]
                          for name, c in zip(names, host[1:])}
        pidx, pcols, pvalid = out
        names = list(pcols)
        host = jax.device_get([pidx, pvalid] + [pcols[n] for n in names])
        pidx_h, sel = host[0], host[1]
        keys = self.index.slot_key[pidx_h[sel]]
        return keys, {name: c[sel]
                      for name, c in zip(names, host[2:])}

    def _harvest_rows_async(self, rows: np.ndarray):
        """Async form of :meth:`_harvest_rows`: dispatch + PendingFire.
        The key rows backing the result are snapshotted at dispatch
        (keys are append-only, so rows < used never mutate, but the
        copy also survives an index grow/realloc)."""
        from flink_tpu.runtime.pending import PendingFire

        used = self.used_cols
        out = self._fire_rows(self.accs, jnp.asarray(rows), used)
        if self.fire_projector is None:
            cols, valid = out
            names = list(cols.keys())
            keys_snap = self.index.slot_key[:used].copy()

            def build(host: List[np.ndarray]):
                sel = host[0][:used]
                return keys_snap[sel], {
                    name: col[:used][sel]
                    for name, col in zip(names, host[1:])}

            return PendingFire([valid] + [cols[n] for n in names], build)
        pidx, pcols, pvalid = out
        names = list(pcols.keys())
        keys_snap = self.index.slot_key[:used].copy()

        def build(host: List[np.ndarray]):
            pidx_h, sel = host[0], host[1]
            return keys_snap[pidx_h[sel]], {
                name: col[sel] for name, col in zip(names, host[2:])}

        return PendingFire([pidx, pvalid] + [pcols[n] for n in names],
                           build)

    # ----------------------------------------------------------------- frees

    def free_slices(self, slice_ends: List[int]) -> None:
        for se in slice_ends:
            row = self.slice_row.pop(int(se), None)
            if row is None:
                continue
            self.accs = self._reset_row(self.accs, row)
            self._free_rows.append(row)
            self._dirty_slices.discard(int(se))
            self._freed_ns.append(int(se))
        self._maybe_compact()

    #: alias so PaneWindower shares SliceSharedWindower.on_watermark
    free_namespaces = free_slices

    #: no spill tier in the pane layout (the slot layout covers that)
    spill = frozenset()

    _COMPACT_MIN_KEYS = 4096

    def _maybe_compact(self) -> None:
        """Key columns are never freed inline (a key's column is shared by
        every live slice), so key churn would grow the table forever —
        when most allocated columns belong to departed keys, rebuild the
        table from its own logical snapshot (one state round-trip,
        amortized rare; the slot layout's free_namespaces analog).

        The aliveness probe reads a device reduction (one link RTT), so it
        only runs when the key high-water mark has grown >=1.5x since the
        last probe: compaction exists to reclaim columns as the table
        GROWS toward capacity — a stable keyset (hw flat) needs neither
        the probe nor the rebuild, and previously paid one blocking fetch
        per watermark advance for it."""
        hw = self._high_water
        if hw < self._COMPACT_MIN_KEYS:
            return
        if hw < getattr(self, "_compact_probed_hw", 0) * 3 // 2:
            return
        self._compact_probed_hw = hw
        live = sorted(self.slice_row)
        if live:
            rows = np.asarray([self.slice_row[se] for se in live],
                              dtype=np.int32)
            alive = int(np.asarray(
                (self.accs[-1][rows].max(axis=0) > 0)[:hw]).sum())
        else:
            alive = 0
        if alive * 2 > hw:
            return
        snap = self.snapshot(reset_dirty=False)
        dirty, freed = self._dirty_slices, self._freed_ns
        wins = sorted(self.window_row)  # derived rows: rebuilt below
        self.index = make_slot_index(self.index.capacity,
                                     on_grow=self._grow_cols)
        self.capacity = self.index.capacity
        self._high_water = 1
        self.slice_row = {}
        self.window_row = {}
        self._free_rows = list(range(self.R - 1, 0, -1))
        self.accs = tuple(
            jnp.full((self.R, self.capacity), l.identity, dtype=l.dtype)
            for l in self.agg.leaves
        ) + (jnp.zeros((self.R, self.capacity), dtype=jnp.int8),)
        self.restore(snap)
        # compaction must not eat incremental bookkeeping: every surviving
        # slice moved, so they are all dirty vs the last base
        self._dirty_slices = set(dirty) | set(self.slice_row)
        self._freed_ns = freed
        # window partials are derived state — refold them from the
        # compacted panes (preagg mode; no-op without the mapping)
        self.rebuild_window_partials(wins)

    # ------------------------------------------------------------ point query

    def query_windows(self, key_id: int, assigner) -> Dict[int, dict]:
        col = self.index.lookup(np.asarray([key_id], dtype=np.int64),
                                np.zeros(1, dtype=np.int64))[0]
        if col < 0:
            return {}
        live = sorted(self.slice_row)
        if not live:
            return {}
        rows = np.asarray([self.slice_row[se] for se in live],
                          dtype=np.int32)
        # ONE batched D2H for every leaf plane (per-plane np.asarray
        # pays one link round-trip per leaf)
        picked = jax.device_get([a[rows, int(col)] for a in self.accs])
        per_leaf, present = picked[:-1], picked[-1] > 0
        slice_vals = {
            se: tuple(pl[i] for pl in per_leaf)
            for i, se in enumerate(live) if present[i]
        }
        if not slice_vals:
            return {}
        windows = sorted({
            int(w) for se in slice_vals
            for w in assigner.window_ends_for_slice(se)})
        out = {}
        idents = tuple(l.identity for l in self.agg.leaves)
        host_merge = {"sum": np.add, "max": np.maximum, "min": np.minimum}
        for w in windows:
            acc = list(idents)
            hit = False
            for se in assigner.slice_ends_for_window(w):
                sv = slice_vals.get(int(se))
                if sv is None:
                    continue
                hit = True
                for i, l in enumerate(self.agg.leaves):
                    acc[i] = host_merge[l.reduce](acc[i], sv[i])
            if not hit:
                continue
            merged = tuple(np.asarray([v]) for v in acc)
            finished = self.agg.finish(merged)
            out[w] = {name: np.asarray(v)[0].item()
                      for name, v in finished.items()}
        return out

    # -------------------------------------------------------------- snapshot

    def snapshot(self, reset_dirty: bool = True) -> Dict[str, np.ndarray]:
        """Logical rows — SAME format as SlotTable.snapshot (key_id /
        namespace / key_group / leaf_i), so pane and slot checkpoints are
        mutually restorable."""
        live = sorted(self.slice_row)
        return self._snapshot_slices(live, reset_dirty=reset_dirty,
                                     delta=False)

    def snapshot_delta(self) -> Dict[str, np.ndarray]:
        """Sealed slices never dirty again — the delta is just the slices
        touched since the last snapshot plus freed tombstones."""
        dirty = sorted(self._dirty_slices)
        out = self._snapshot_slices(dirty, reset_dirty=True, delta=True)
        return out

    def _snapshot_slices(self, slices: List[int], reset_dirty: bool,
                         delta: bool) -> Dict[str, np.ndarray]:
        used = self.used_cols
        key_cols, ns_cols = [], []
        leaf_cols: List[List[np.ndarray]] = [[] for _ in self.agg.leaves]
        if slices:
            # ONE batched gather + D2H for every snapshotted slice row
            # (the per-slice-per-leaf np.asarray loop paid one link
            # round-trip for each)
            row_ids = np.asarray([self.slice_row[se] for se in slices],
                                 dtype=np.int32)
            rows_host = jax.device_get(
                [a[row_ids, :used] for a in self.accs])
        for j, se in enumerate(slices):
            present = rows_host[-1][j] > 0
            if not present.any():
                continue
            keys = self.index.slot_key[:used][present]
            key_cols.append(keys)
            ns_cols.append(np.full(len(keys), se, dtype=np.int64))
            for i in range(len(self.agg.leaves)):
                leaf_cols[i].append(rows_host[i][j][present])
        if key_cols:
            key_ids = np.concatenate(key_cols)
            out = {
                "key_id": key_ids,
                "namespace": np.concatenate(ns_cols),
                "key_group": assign_key_groups(key_ids,
                                               self.max_parallelism),
                **{f"leaf_{i}": np.concatenate(cols)
                   for i, cols in enumerate(leaf_cols)},
            }
        else:
            out = {
                "key_id": np.empty(0, dtype=np.int64),
                "namespace": np.empty(0, dtype=np.int64),
                "key_group": np.empty(0, dtype=np.int32),
                **{f"leaf_{i}": np.empty(0, dtype=l.dtype)
                   for i, l in enumerate(self.agg.leaves)},
            }
        if delta:
            out["__delta__"] = np.asarray(True)
            out["freed_namespaces"] = np.asarray(
                sorted(set(self._freed_ns)), dtype=np.int64)
        if reset_dirty:
            self._dirty_slices.clear()
            self._freed_ns.clear()
        return out

    def restore(self, snap: Dict[str, np.ndarray],
                key_group_filter=None) -> None:
        key_ids = np.asarray(snap["key_id"], dtype=np.int64)
        namespaces = np.asarray(snap["namespace"], dtype=np.int64)
        leaves = []
        for i, leaf in enumerate(self.agg.leaves):
            arr = np.asarray(snap[f"leaf_{i}"])
            want = np.dtype(leaf.dtype)
            if len(arr) and arr.dtype != want:
                # same schema-compatibility contract as SlotTable.restore:
                # a value-preserving cast migrates, a lossy one fails
                cast = arr.astype(want)
                if not np.array_equal(cast.astype(arr.dtype), arr):
                    raise RuntimeError(
                        f"state schema incompatible: snapshot leaf_{i} "
                        f"has dtype {arr.dtype}, the aggregate expects "
                        f"{want} and the values do not survive the cast")
                arr = cast
            leaves.append(arr.astype(want))
        if key_group_filter is not None and len(key_ids):
            groups = assign_key_groups(key_ids, self.max_parallelism)
            keep = np.isin(groups, np.asarray(sorted(key_group_filter)))
            key_ids, namespaces = key_ids[keep], namespaces[keep]
            leaves = [l[keep] for l in leaves]
        order = np.argsort(namespaces, kind="stable")
        key_ids, namespaces = key_ids[order], namespaces[order]
        leaves = [l[order] for l in leaves]
        bounds = np.nonzero(np.diff(namespaces))[0] + 1
        starts = np.concatenate(([0], bounds)) if len(key_ids) else []
        ends = np.concatenate((bounds, [len(key_ids)])) if len(key_ids) \
            else []
        for a, b in zip(list(starts), list(ends)):
            se = int(namespaces[a])
            row = self.slice_row.get(se)
            if row is None:
                row = self._alloc_row(se)
            cols = self.index.lookup_or_insert(
                key_ids[a:b], np.zeros(b - a, dtype=np.int64))
            if len(cols):
                self._high_water = max(self._high_water,
                                       int(cols.max()) + 1)
            self.accs = self._put_row(
                self.accs, row,
                jnp.asarray(cols.astype(np.int32)),
                tuple(jnp.asarray(l[a:b]) for l in leaves))
        self._dirty_slices.clear()
        self._freed_ns.clear()
