"""Async keyed state — StateFuture-returning handles for process functions.

reference: State V2 (flink-runtime/.../runtime/state/v2/, 55 files) exposes
StateFuture-returning Value/List/Map/Reducing states; the
AsyncExecutionController (runtime/asyncprocessing/AsyncExecutionController.java:57)
buffers StateRequests (batchSize/bufferTimeout, :67,364-369), serializes
same-key accesses via KeyAccountingUnit, and executes batches through
StateExecutor.executeBatchRequests (the ForSt backend groups them into one
multiGet / write-batch — ForStStateExecutor.java:149).

Batched re-design: the reference buffers *per-record scalar* requests to
recover batching the record-at-a-time API destroyed. This engine is already
batch-native — a single async op carries a whole key VECTOR — so the
controller's job shifts one level up: coalesce *independent op vectors*
into single fused kernels while preserving the reference's ordering
contract (same-key ops serialize in submission order; disjoint-key ops
merge freely). Ops queue into WAVES: an op joins the open wave unless one
of its keys conflicts with an earlier op in that wave (read-after-write,
write-after-read, or cross-kind write-after-write); a conflict seals the
wave. At drain, each wave executes one vectorized kernel per
(state, op-kind) group — N same-kind ops on disjoint keys cost one gather
or one scatter regardless of N.

Two executors sit under the same future API:
- host states (ValueState/ReducingState/MapState of keyed_state.py) — the
  win is kernel coalescing;
- DeviceValueState — accumulators committed to the accelerator
  (state.backend placement, backends.py); wave execution *dispatches*
  gathers/scatters without blocking, so device latency overlaps host
  processing exactly the way window fires already overlap
  (runtime/pending.py). Only ``StateFuture.value()`` forces a transfer.

Drain points follow the reference: end of every operator invocation and
before every snapshot (AsyncExecutionController.drainInflightRecords) — a
checkpoint never captures un-executed state ops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.core.annotations import public
from flink_tpu.state.keyed_state import (
    ListState,
    MapState,
    MapStateDescriptor,
    ReducingState,
    ReducingStateDescriptor,
    ValueState,
    ValueStateDescriptor,
)

# op kinds
_GET, _PUT, _ADD, _CLEAR = "get", "put", "add", "clear"
_READS = (_GET,)
_WRITES = (_PUT, _ADD, _CLEAR)


@public
class StateFuture:
    """Result of one async state op.

    reference: api/common/state/v2/StateFuture.java — thenAccept /
    thenApply composition; completion happens on the task thread at
    drain, never concurrently with user code.
    """

    __slots__ = ("_controller", "_done", "_value", "_callbacks")

    def __init__(self, controller: "AsyncExecutionController"):
        self._controller = controller
        self._done = False
        self._value = None
        self._callbacks: List[Tuple[Callable, "StateFuture"]] = []

    @property
    def done(self) -> bool:
        return self._done

    def value(self):
        """Force: drains the controller if this op hasn't executed yet.
        Device-backed results materialize to host NumPy here (the one
        place a device transfer is allowed to block)."""
        if not self._done:
            self._controller.drain()
        v = self._value
        if v is not None and not isinstance(v, np.ndarray) \
                and hasattr(v, "__array__"):
            v = np.asarray(v)  # force a lazily-sliced device array
            self._value = v
        return v

    def then(self, fn: Callable[[Any], Any]) -> "StateFuture":
        """Chain ``fn(result)``; returns a future for fn's return value.
        Runs at completion on the task thread (reference: thenApply —
        callbacks re-enqueued as mail on the mailbox thread)."""
        out = StateFuture(self._controller)
        if self._done:
            out._complete(fn(self.value()))
        else:
            self._callbacks.append((fn, out))
        return out

    def _complete(self, value) -> None:
        self._done = True
        self._value = value
        cbs, self._callbacks = self._callbacks, []
        for fn, out in cbs:
            out._complete(fn(self.value()))


@dataclasses.dataclass
class _Op:
    state: Any          # executor adapter (async handle)
    kind: str
    key_ids: np.ndarray
    payload: Any        # values (put/add) / map_keys tuple (map ops) / None
    future: StateFuture


class _Wave:
    """One conflict-free group of ops: executes as one kernel per
    (state, kind) group."""

    def __init__(self):
        self.ops: List[_Op] = []
        # per-state key footprints for conflict checks
        self._reads: Dict[int, set] = {}
        self._writes: Dict[int, set] = {}       # keys written, any kind
        self._write_kind: Dict[int, str] = {}   # state id -> sole write kind

    def admits(self, op: _Op, keys: set) -> bool:
        sid = id(op.state)
        if op.kind in _READS:
            # read-after-write in the same wave would see stale values
            return not (self._writes.get(sid) and
                        keys & self._writes[sid])
        # writes: conflict with earlier reads (order would flip) and with
        # earlier writes of a DIFFERENT kind (put vs add don't commute);
        # same-kind writes merge — concatenation preserves submission
        # order (NumPy scatter is last-wins in array order, ufunc.at
        # accumulates), so duplicates stay correct.
        if self._reads.get(sid) and keys & self._reads[sid]:
            return False
        if self._writes.get(sid) and self._write_kind.get(sid) != op.kind \
                and keys & self._writes[sid]:
            return False
        return True

    def add(self, op: _Op, keys: set) -> None:
        sid = id(op.state)
        if op.kind in _READS:
            self._reads.setdefault(sid, set()).update(keys)
        else:
            self._writes.setdefault(sid, set()).update(keys)
            self._write_kind[sid] = op.kind \
                if self._write_kind.get(sid, op.kind) == op.kind else "mixed"
        self.ops.append(op)


class AsyncExecutionController:
    """Buffers async state ops and executes them in coalesced waves.

    reference: runtime/asyncprocessing/AsyncExecutionController.java:57
    (StateRequestBuffer + KeyAccountingUnit + StateExecutor). ``stats``
    counts ops/waves/kernel calls so tests can assert the coalescing
    contract instead of trusting it.
    """

    def __init__(self):
        self._waves: List[_Wave] = []
        self.stats = {"ops": 0, "waves": 0, "kernel_calls": 0}

    # -- submission ----------------------------------------------------------

    def submit(self, state, kind: str, key_ids, payload=None) -> StateFuture:
        op = _Op(state, kind, np.atleast_1d(
            np.asarray(key_ids, dtype=np.int64)), payload,
            StateFuture(self))
        keys = set(op.key_ids.tolist())
        if not self._waves or not self._waves[-1].admits(op, keys):
            self._waves.append(_Wave())
        self._waves[-1].add(op, keys)
        self.stats["ops"] += 1
        return op.future

    @property
    def pending(self) -> int:
        return sum(len(w.ops) for w in self._waves)

    # -- execution -----------------------------------------------------------

    def drain(self) -> None:
        """Execute everything pending, in wave order. Callbacks may submit
        new ops; the loop runs until the queue is empty (reference:
        drainInflightRecords loops until allRequestsDone)."""
        while self._waves:
            waves, self._waves = self._waves, []
            for wave in waves:
                self._execute(wave)

    def _execute(self, wave: _Wave) -> None:
        self.stats["waves"] += 1
        # group by (state, kind) in first-appearance order
        groups: Dict[Tuple[int, str], List[_Op]] = {}
        for op in wave.ops:
            groups.setdefault((id(op.state), op.kind), []).append(op)
        for ops in groups.values():
            state, kind = ops[0].state, ops[0].kind
            keys = np.concatenate([o.key_ids for o in ops])
            self.stats["kernel_calls"] += 1
            if kind == _GET:
                res = state._exec_get(keys, ops)
                # split the batched result back per op
                offs = np.cumsum([len(o.key_ids) for o in ops])[:-1]
                parts = (res if isinstance(res, list)
                         else _split(res, offs))
                for o, part in zip(ops, parts):
                    o.future._complete(part)
            elif kind == _PUT:
                state._exec_put(keys, ops)
                for o in ops:
                    o.future._complete(None)
            elif kind == _ADD:
                state._exec_add(keys, ops)
                for o in ops:
                    o.future._complete(None)
            else:  # _CLEAR
                state._exec_clear(keys)
                for o in ops:
                    o.future._complete(None)


def _split(arr, offsets):
    return np.split(arr, offsets) if isinstance(arr, np.ndarray) \
        else [arr[a:b] for a, b in _ranges(offsets, _len(arr))]


def _ranges(offsets, n):
    starts = [0] + list(offsets)
    ends = list(offsets) + [n]
    return zip(starts, ends)


def _len(arr):
    return arr.shape[0]


def _concat_payload(ops: List[_Op]) -> np.ndarray:
    return np.concatenate([
        np.broadcast_to(np.asarray(o.payload), o.key_ids.shape)
        for o in ops])


# --------------------------------------------------------------------------
# Async handles over the host states
# --------------------------------------------------------------------------


@public
class AsyncValueState:
    """StateFuture-returning view of a (host or device) value state.

    reference: runtime/state/v2/ValueState.java — asyncValue()/
    asyncUpdate(); here vectorized per the engine's batch contract.
    """

    def __init__(self, controller: AsyncExecutionController, sync: ValueState):
        self._aec = controller
        self._sync = sync

    # async API
    def get(self, key_ids) -> StateFuture:
        return self._aec.submit(self, _GET, key_ids)

    def put(self, key_ids, values) -> StateFuture:
        return self._aec.submit(self, _PUT, key_ids, values)

    def clear(self, key_ids) -> StateFuture:
        return self._aec.submit(self, _CLEAR, key_ids)

    # executor hooks (one vectorized sync call == one kernel)
    def _exec_get(self, keys, ops):
        return self._sync.get(keys)

    def _exec_put(self, keys, ops):
        self._sync.put(keys, _concat_payload(ops))

    def _exec_clear(self, keys):
        self._sync.clear(keys)


@public
class AsyncReducingState(AsyncValueState):
    """reference: runtime/state/v2/ReducingState.java asyncAdd()."""

    def add(self, key_ids, values) -> StateFuture:
        return self._aec.submit(self, _ADD, key_ids, values)

    def _exec_add(self, keys, ops):
        self._sync.add(keys, _concat_payload(ops))


@public
class AsyncMapState:
    """reference: runtime/state/v2/MapState.java asyncGet/asyncPut.
    Vectorized over (key_id, map_key) pairs; executes through the host
    MapState (variable-size state never hits the device), so the async
    win here is ordering + batching with other states' ops, not kernels.
    """

    def __init__(self, controller: AsyncExecutionController, sync: MapState):
        self._aec = controller
        self._sync = sync

    def get(self, key_ids, map_keys, default=None) -> StateFuture:
        return self._aec.submit(self, _GET, key_ids,
                                (list(map_keys), default))

    def put(self, key_ids, map_keys, values) -> StateFuture:
        return self._aec.submit(self, _PUT, key_ids,
                                (list(map_keys), list(values)))

    def clear(self, key_ids) -> StateFuture:
        return self._aec.submit(self, _CLEAR, key_ids)

    def _exec_get(self, keys, ops):
        out = []
        for o in ops:
            mkeys, default = o.payload
            out.append([self._sync.get(int(k), mk, default)
                        for k, mk in zip(o.key_ids.tolist(), mkeys)])
        return out

    def _exec_put(self, keys, ops):
        for o in ops:
            mkeys, vals = o.payload
            for k, mk, v in zip(o.key_ids.tolist(), mkeys, vals):
                self._sync.put(k, mk, v)

    def _exec_clear(self, keys):
        self._sync.clear(keys)


# --------------------------------------------------------------------------
# Device-resident value state
# --------------------------------------------------------------------------

#: gather/scatter kernels shared by EVERY DeviceValueState — a
#: per-instance ``jax.jit(lambda ...)`` is a fresh jit identity per
#: state object, i.e. a full XLA recompile for each (flint JIT01);
#: built lazily because jax imports are deferred in this module
_DEVICE_KERNEL_CACHE: dict = {}


def _device_value_kernels():
    fns = _DEVICE_KERNEL_CACHE.get("kernels")
    if fns is None:
        import jax
        import jax.numpy as jnp

        fns = (
            jax.jit(lambda v, s: jnp.take(v, s, axis=0, mode="clip")),
            jax.jit(lambda v, s, x: v.at[s].set(x), donate_argnums=0),
        )
        _DEVICE_KERNEL_CACHE["kernels"] = fns
    return fns


class DeviceValueState(ValueState):
    """ValueState whose dense array lives on the accelerator.

    The snapshot/restore/grow/TTL machinery is inherited; only the
    storage and the batched kernels differ: values are a jax array
    committed to the state backend's placement (backends.py), gathers and
    scatters are jitted device kernels, and — the point — gathers
    DISPATCH asynchronously. A wave of async gets costs one device
    round-trip that overlaps whatever the host does next; results only
    materialize at ``StateFuture.value()``.

    reference: the ForSt backend's executeBatchRequests
    (ForStStateExecutor.java:149) — one multiGet per request batch
    against storage that is not the JVM heap.
    """

    def __init__(self, store, desc: ValueStateDescriptor, device=None):
        if getattr(desc, "ttl", None) is not None:
            raise ValueError(
                "DeviceValueState does not support TTL yet; keep TTL'd "
                "state on the host backend (state.backend=host-heap)")
        super().__init__(store, dataclasses.replace(desc, ttl=None))
        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        dtype = np.dtype(desc.dtype)
        arr = jnp.full(store.capacity, desc.default, dtype=dtype)
        self._device = device
        self._dvals = jax.device_put(arr, device) if device is not None \
            else arr
        self._gather, self._scatter = _device_value_kernels()
        self._host_dirty = False  # host mirror (self._values) staleness

    # -- device kernels ------------------------------------------------------

    def _slots(self, key_ids):
        return self._store.slots(key_ids)

    def get(self, key_ids):
        """Sync get: gather + materialize (blocks on the device)."""
        return np.asarray(self._gather(self._dvals, self._slots(key_ids)))

    def put(self, key_ids, values) -> None:
        vals = np.broadcast_to(
            np.asarray(values, dtype=self._values.dtype),
            np.atleast_1d(np.asarray(key_ids)).shape)
        self._dvals = self._scatter(self._dvals, self._slots(key_ids), vals)
        self._host_dirty = True

    def clear(self, key_ids) -> None:
        self.put(key_ids, self.desc.default)

    # executor hooks: gather returns the DEVICE array (no block); the
    # controller slices it per op and only value() forces a transfer.
    def _exec_get(self, keys, ops):
        return self._gather(self._dvals, self._slots(keys))

    def _exec_put(self, keys, ops):
        self.put(keys, _concat_payload(ops))

    def _exec_clear(self, keys):
        self.put(keys, self.desc.default)

    # -- growth / checkpoint -------------------------------------------------

    def _on_grow(self, old: int, new: int) -> None:
        super()._on_grow(old, new)
        jnp = self._jnp
        grown = jnp.full(new, self.desc.default,
                         dtype=self._values.dtype)
        self._dvals = grown.at[:old].set(self._dvals)

    def snapshot(self) -> Dict[str, Any]:
        return {"values": np.asarray(self._dvals).copy()}

    def restore(self, snap, slot_remap=None) -> None:
        super().restore(snap, slot_remap=slot_remap)
        import jax

        arr = self._jnp.asarray(self._values)
        self._dvals = jax.device_put(arr, self._device) \
            if self._device is not None else arr


@public
@dataclasses.dataclass(frozen=True)
class DeviceValueStateDescriptor(ValueStateDescriptor):
    """ValueStateDescriptor whose storage commits to the accelerator."""


# register with the store's descriptor dispatch
from flink_tpu.state import keyed_state as _ks  # noqa: E402

_ks._STATE_TYPES[DeviceValueStateDescriptor] = DeviceValueState


def make_async_view(controller: AsyncExecutionController, sync_state):
    """Wrap a sync state handle in its async view."""
    if isinstance(sync_state, ReducingState):
        return AsyncReducingState(controller, sync_state)
    if isinstance(sync_state, ValueState):  # incl. DeviceValueState
        return AsyncValueState(controller, sync_state)
    if isinstance(sync_state, MapState):
        return AsyncMapState(controller, sync_state)
    raise TypeError(
        f"no async view for state type {type(sync_state).__name__} "
        "(ListState stays sync: append-only host logs gain nothing "
        "from coalescing)")
