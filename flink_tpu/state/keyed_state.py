"""User-facing keyed state primitives for process functions.

reference: flink-runtime/.../runtime/state/KeyedStateBackend.java
(getPartitionedState), heap/HeapValueState.java, HeapListState.java,
HeapMapState.java, HeapReducingState.java; descriptors in
flink-core/.../api/common/state/StateDescriptor.java.

Batched re-design: where the reference exposes per-key scalar handles bound
to a "current key" (``setCurrentKey`` before every access —
AbstractKeyedStateBackend.java), these states expose **vectorized** handles:
every read/write takes an ``int64`` array of key ids and operates on the
whole batch at once. Fixed-dtype values (Value/Reducing) live in dense NumPy
arrays indexed by slot (one ``HostSlotIndex`` shared per operator — the same
host half used by the device SlotTable); variable-size values (List/Map)
live in host dicts, which never reach the device.

All states snapshot/restore for checkpointing and are partitioned by key
group for rescale (key id -> key group is recomputed from the key id, so a
restore with a different parallelism reassigns transparently).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.state.slot_table import make_slot_index
from flink_tpu.state.ttl import StateTtlConfig, TtlStamps, default_clock

_NS = 0  # process-function state has no window namespace


from flink_tpu.core.annotations import public

@public
@dataclasses.dataclass(frozen=True)
class ValueStateDescriptor:
    name: str
    dtype: Any = np.float64
    default: Any = 0
    #: reference: StateDescriptor.enableTimeToLive(StateTtlConfig)
    ttl: Optional[StateTtlConfig] = None


@public
@dataclasses.dataclass(frozen=True)
class ReducingStateDescriptor:
    """``reduce`` must be a binary NumPy ufunc-like (np.add, np.maximum, ...)
    so batch folds stay vectorized (``ufunc.at`` scatter)."""

    name: str
    reduce: Any = None
    dtype: Any = np.float64
    default: Any = 0
    ttl: Optional[StateTtlConfig] = None


@public
@dataclasses.dataclass(frozen=True)
class ListStateDescriptor:
    name: str
    ttl: Optional[StateTtlConfig] = None


@public
@dataclasses.dataclass(frozen=True)
class MapStateDescriptor:
    name: str
    ttl: Optional[StateTtlConfig] = None


class ValueState:
    """Dense vectorized value-per-key state."""

    def __init__(self, store: "KeyedStateStore", desc: ValueStateDescriptor):
        self._store = store
        self.desc = desc
        self._values = np.full(store.capacity, desc.default,
                               dtype=np.dtype(desc.dtype))
        self._ttl = (TtlStamps(store.capacity, desc.ttl)
                     if getattr(desc, "ttl", None) is not None else None)

    def _on_grow(self, old: int, new: int) -> None:
        grown = np.full(new, self.desc.default, dtype=self._values.dtype)
        grown[:old] = self._values
        self._values = grown
        if self._ttl is not None:
            self._ttl.grow(old, new)

    def get(self, key_ids: np.ndarray) -> np.ndarray:
        slots = self._store.slots(key_ids)
        if self._ttl is None:
            return self._values[slots]
        now = self._store.now_ms()
        out = self._values[slots]
        hidden = self._ttl.hidden_mask(slots, now)
        if hidden.any():
            out = out.copy()
            out[hidden] = self.desc.default
        self._ttl.touch_on_read(slots, now)
        return out

    def put(self, key_ids: np.ndarray, values) -> None:
        slots = self._store.slots(key_ids)
        self._values[slots] = values
        if self._ttl is not None:
            self._ttl.touch(slots, self._store.now_ms())

    def clear(self, key_ids: np.ndarray) -> None:
        slots = self._store.slots(key_ids)
        self._values[slots] = self.desc.default
        if self._ttl is not None:
            self._ttl.clear(slots)

    def sweep_expired(self, now_ms: int) -> int:
        """Vectorized expiry sweep (reference: TtlStateFactory cleanup
        strategies collapsed into one masked reset)."""
        if self._ttl is None:
            return 0
        expired = self._ttl.sweep(now_ms)
        self._values[expired] = self.desc.default
        return len(expired)

    def snapshot(self) -> Dict[str, Any]:
        snap = {"values": self._values.copy()}
        if self._ttl is not None:
            snap["ttl_stamps"] = self._ttl.snapshot()
        return snap

    def restore(self, snap: Dict[str, Any], slot_remap=None) -> None:
        vals = snap["values"]
        if slot_remap is not None:
            self._values[slot_remap[1]] = vals[slot_remap[0]]
        else:
            self._values[: len(vals)] = vals
        if self._ttl is not None and "ttl_stamps" in snap:
            # stamps restore as-is: remaining lifetime continues from
            # the original access time (reference restore semantics)
            self._ttl.restore(snap["ttl_stamps"], slot_remap=slot_remap)


class ReducingState(ValueState):
    def __init__(self, store, desc: ReducingStateDescriptor):
        super().__init__(store, ValueStateDescriptor(
            desc.name, desc.dtype, desc.default,
            ttl=getattr(desc, "ttl", None)))
        self.reduce = desc.reduce if desc.reduce is not None else np.add

    def add(self, key_ids: np.ndarray, values) -> None:
        """Fold a batch in with one scatter (``ufunc.at`` handles duplicate
        keys within the batch in order)."""
        slots = self._store.slots(key_ids)
        if self._ttl is not None:
            now = self._store.now_ms()
            # folding into an expired entry restarts from the default —
            # the stale accumulator must not leak into the new lifetime
            expired = self._ttl.expired_mask(slots, now)
            if expired.any():
                self._values[slots[expired]] = self.desc.default
            self.reduce.at(self._values, slots, values)
            self._ttl.touch(slots, now)
            return
        self.reduce.at(self._values, slots, values)


class _HostTtl:
    """Per-key last-access stamps for the host-dict states (List/Map) —
    the dict analog of TtlStamps. ``now_ms`` is passed in so hot loops
    fetch the clock once per batch, not per element."""

    def __init__(self, store: "KeyedStateStore", cfg: StateTtlConfig):
        self._store = store
        self.cfg = cfg
        self.stamps: Dict[int, int] = {}

    def touch(self, k: int, now_ms: int) -> None:
        self.stamps[k] = now_ms

    def touch_on_read(self, k: int, now_ms: int) -> None:
        from flink_tpu.state.ttl import ON_READ_AND_WRITE

        if self.cfg.update_type == ON_READ_AND_WRITE \
                and not self.is_expired(k, now_ms):
            self.stamps[k] = now_ms

    def is_expired(self, k: int, now_ms: int) -> bool:
        s = self.stamps.get(k)
        return s is not None and now_ms - s > self.cfg.ttl_ms

    def is_hidden(self, k: int, now_ms: int) -> bool:
        from flink_tpu.state.ttl import RETURN_EXPIRED_IF_NOT_CLEANED_UP

        if self.cfg.visibility == RETURN_EXPIRED_IF_NOT_CLEANED_UP:
            return False
        return self.is_expired(k, now_ms)

    def sweep(self, now_ms: int) -> List[int]:
        dead = [k for k, s in self.stamps.items()
                if now_ms - s > self.cfg.ttl_ms]
        for k in dead:
            del self.stamps[k]
        return dead


class ListState:
    """Append-log per key; host-resident (variable size never hits HBM)."""

    def __init__(self, store: "KeyedStateStore", desc: ListStateDescriptor):
        self.desc = desc
        self._lists: Dict[int, list] = {}
        self._ttl = (_HostTtl(store, desc.ttl)
                     if getattr(desc, "ttl", None) is not None else None)

    def _now(self) -> int:
        return self._ttl._store.now_ms()

    def add(self, key_ids: np.ndarray, values) -> None:
        lists = self._lists
        vals = np.asarray(values)
        ttl = self._ttl
        now = self._now() if ttl is not None else 0
        for k, v in zip(np.asarray(key_ids).tolist(), vals.tolist()):
            if ttl is not None:
                if ttl.is_expired(k, now):
                    lists.pop(k, None)
                ttl.touch(k, now)
            lists.setdefault(k, []).append(v)

    def get(self, key_id: int) -> list:
        k = int(key_id)
        if self._ttl is not None:
            now = self._now()
            if self._ttl.is_hidden(k, now):
                return []
            self._ttl.touch_on_read(k, now)
        return self._lists.get(k, [])

    def clear(self, key_ids) -> None:
        for k in np.atleast_1d(np.asarray(key_ids)).tolist():
            self._lists.pop(int(k), None)
            if self._ttl is not None:
                self._ttl.stamps.pop(int(k), None)

    def keys(self) -> List[int]:
        if self._ttl is None:
            return list(self._lists)
        # iteration must agree with get(): expired-but-unswept keys are
        # invisible, not phantom entries with empty state
        now = self._now()
        return [k for k in self._lists
                if not self._ttl.is_hidden(k, now)]

    def sweep_expired(self, now_ms: int) -> int:
        if self._ttl is None:
            return 0
        dead = self._ttl.sweep(now_ms)
        for k in dead:
            self._lists.pop(k, None)
        return len(dead)

    def snapshot(self):
        snap = {"lists": {k: list(v) for k, v in self._lists.items()}}
        if self._ttl is not None:
            snap["ttl_stamps"] = dict(self._ttl.stamps)
        return snap

    def restore(self, snap, slot_remap=None):
        self._lists = {int(k): list(v) for k, v in snap["lists"].items()}
        if self._ttl is not None:
            self._ttl.stamps = {
                int(k): int(v)
                for k, v in snap.get("ttl_stamps", {}).items()}


class MapState:
    """Per-key hash map; host-resident.

    TTL granularity is the KEY (whole map), not the map entry — the
    coarser unit fits the columnar engine's per-slot stamps; the
    reference stamps per map ENTRY (TtlMapState), which this trades
    away for not touching a dict per access."""

    def __init__(self, store: "KeyedStateStore", desc: MapStateDescriptor):
        self.desc = desc
        self._maps: Dict[int, dict] = {}
        self._ttl = (_HostTtl(store, desc.ttl)
                     if getattr(desc, "ttl", None) is not None else None)

    def _now(self) -> int:
        return self._ttl._store.now_ms()

    def _live(self, k: int, now: int) -> dict:
        if self._ttl is not None and self._ttl.is_hidden(k, now):
            return {}
        return self._maps.get(k, {})

    def put(self, key_id: int, map_key, value) -> None:
        k = int(key_id)
        if self._ttl is not None:
            now = self._now()
            if self._ttl.is_expired(k, now):
                self._maps.pop(k, None)
            self._ttl.touch(k, now)
        self._maps.setdefault(k, {})[map_key] = value

    def get(self, key_id: int, map_key, default=None):
        k = int(key_id)
        now = self._now() if self._ttl is not None else 0
        out = self._live(k, now).get(map_key, default)
        if self._ttl is not None:
            self._ttl.touch_on_read(k, now)
        return out

    def contains(self, key_id: int, map_key) -> bool:
        now = self._now() if self._ttl is not None else 0
        return map_key in self._live(int(key_id), now)

    def remove(self, key_id: int, map_key) -> None:
        self._maps.get(int(key_id), {}).pop(map_key, None)

    def entries(self, key_id: int) -> dict:
        k = int(key_id)
        now = self._now() if self._ttl is not None else 0
        out = self._live(k, now)
        if self._ttl is not None:
            self._ttl.touch_on_read(k, now)
        return out

    def clear(self, key_ids) -> None:
        for k in np.atleast_1d(np.asarray(key_ids)).tolist():
            self._maps.pop(int(k), None)
            if self._ttl is not None:
                self._ttl.stamps.pop(int(k), None)

    def sweep_expired(self, now_ms: int) -> int:
        if self._ttl is None:
            return 0
        dead = self._ttl.sweep(now_ms)
        for k in dead:
            self._maps.pop(k, None)
        return len(dead)

    def snapshot(self):
        snap = {"maps": {k: dict(v) for k, v in self._maps.items()}}
        if self._ttl is not None:
            snap["ttl_stamps"] = dict(self._ttl.stamps)
        return snap

    def restore(self, snap, slot_remap=None):
        self._maps = {int(k): dict(v) for k, v in snap["maps"].items()}
        if self._ttl is not None:
            self._ttl.stamps = {
                int(k): int(v)
                for k, v in snap.get("ttl_stamps", {}).items()}


_STATE_TYPES = {
    ValueStateDescriptor: ValueState,
    ReducingStateDescriptor: ReducingState,
    ListStateDescriptor: ListState,
    MapStateDescriptor: MapState,
}


class KeyedStateStore:
    """All keyed states of one operator, sharing one key -> slot index.

    reference: AbstractKeyedStateBackend.java keeps a map of registered
    states per name; state is addressed (key, namespace, name).
    """

    def __init__(self, capacity: int = 1 << 12,
                 clock: Optional[Callable[[], int]] = None):
        self._states: Dict[str, Any] = {}
        self._index = make_slot_index(capacity, on_grow=self._on_grow)
        self.capacity = self._index.capacity
        # states are registered lazily (first ctx.state(desc) call), which
        # can happen after restore — park unclaimed snapshots until then
        self._pending: Dict[str, Any] = {}
        self._pending_remap = None
        #: processing-time source for TTL (injectable for tests)
        self.clock = clock or default_clock

    def now_ms(self) -> int:
        return self.clock()

    def sweep_expired(self, now_ms: Optional[int] = None) -> int:
        """Run the vectorized TTL sweep over every TTL'd state; returns
        entries expired. The runtime calls this on watermark advance
        (the cleanup analog of the reference's background strategies)."""
        now = self.now_ms() if now_ms is None else now_ms
        total = 0
        for st in self._states.values():
            sweep = getattr(st, "sweep_expired", None)
            if sweep is not None:
                total += sweep(now)
        return total

    def _on_grow(self, old: int, new: int) -> None:
        self.capacity = new
        for st in self._states.values():
            if isinstance(st, ValueState):
                st._on_grow(old, new)

    def slots(self, key_ids: np.ndarray) -> np.ndarray:
        kid = np.asarray(key_ids, dtype=np.int64)
        return self._index.lookup_or_insert(
            kid, np.full(len(kid), _NS, dtype=np.int64))

    def get_state(self, desc):
        st = self._states.get(desc.name)
        if st is None:
            st = _STATE_TYPES[type(desc)](self, desc)
            self._states[desc.name] = st
            if desc.name in self._pending:
                st.restore(self._pending.pop(desc.name),
                           slot_remap=self._pending_remap)
        return st

    def known_key_ids(self) -> np.ndarray:
        """All key ids with a slot (dense states) — for full-table scans."""
        used = self._index.used_slots()
        return self._index.slot_key[used]

    def snapshot(self) -> Dict[str, Any]:
        used = self._index.used_slots()
        states = {n: s.snapshot() for n, s in self._states.items()}
        # restored states never re-accessed since restore are still parked in
        # _pending — carry them forward so a restore -> checkpoint -> restore
        # cycle keeps them. Dense ("values") snapshots are indexed by the OLD
        # slot layout; re-home them onto the current layout first.
        for n, s in self._pending.items():
            if "values" in s and self._pending_remap is not None:
                old_slots, new_slots = self._pending_remap
                vals = np.asarray(s["values"])
                rehomed = np.zeros(self.capacity, dtype=vals.dtype)
                rehomed[new_slots] = vals[old_slots]
                s = {"values": rehomed}
            states.setdefault(n, s)
        return {
            "keys": self._index.slot_key[used].copy(),
            "slots": used.copy(),
            "states": states,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        keys = np.asarray(snap["keys"], dtype=np.int64)
        old_slots = np.asarray(snap["slots"])
        # re-insert keys (fresh slot assignment — rescale-safe), then remap
        # dense state rows old slot -> new slot
        new_slots = self.slots(keys)
        remap = (old_slots, new_slots)
        self._pending_remap = remap
        for name, s in snap["states"].items():
            st = self._states.get(name)
            if st is not None:
                st.restore(s, slot_remap=remap)
            else:
                self._pending[name] = s
