"""User-facing keyed state primitives for process functions.

reference: flink-runtime/.../runtime/state/KeyedStateBackend.java
(getPartitionedState), heap/HeapValueState.java, HeapListState.java,
HeapMapState.java, HeapReducingState.java; descriptors in
flink-core/.../api/common/state/StateDescriptor.java.

Batched re-design: where the reference exposes per-key scalar handles bound
to a "current key" (``setCurrentKey`` before every access —
AbstractKeyedStateBackend.java), these states expose **vectorized** handles:
every read/write takes an ``int64`` array of key ids and operates on the
whole batch at once. Fixed-dtype values (Value/Reducing) live in dense NumPy
arrays indexed by slot (one ``HostSlotIndex`` shared per operator — the same
host half used by the device SlotTable); variable-size values (List/Map)
live in host dicts, which never reach the device.

All states snapshot/restore for checkpointing and are partitioned by key
group for rescale (key id -> key group is recomputed from the key id, so a
restore with a different parallelism reassigns transparently).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.state.slot_table import make_slot_index

_NS = 0  # process-function state has no window namespace


from flink_tpu.core.annotations import public

@public
@dataclasses.dataclass(frozen=True)
class ValueStateDescriptor:
    name: str
    dtype: Any = np.float64
    default: Any = 0


@public
@dataclasses.dataclass(frozen=True)
class ReducingStateDescriptor:
    """``reduce`` must be a binary NumPy ufunc-like (np.add, np.maximum, ...)
    so batch folds stay vectorized (``ufunc.at`` scatter)."""

    name: str
    reduce: Any = None
    dtype: Any = np.float64
    default: Any = 0


@public
@dataclasses.dataclass(frozen=True)
class ListStateDescriptor:
    name: str


@public
@dataclasses.dataclass(frozen=True)
class MapStateDescriptor:
    name: str


class ValueState:
    """Dense vectorized value-per-key state."""

    def __init__(self, store: "KeyedStateStore", desc: ValueStateDescriptor):
        self._store = store
        self.desc = desc
        self._values = np.full(store.capacity, desc.default,
                               dtype=np.dtype(desc.dtype))

    def _on_grow(self, old: int, new: int) -> None:
        grown = np.full(new, self.desc.default, dtype=self._values.dtype)
        grown[:old] = self._values
        self._values = grown

    def get(self, key_ids: np.ndarray) -> np.ndarray:
        return self._values[self._store.slots(key_ids)]

    def put(self, key_ids: np.ndarray, values) -> None:
        self._values[self._store.slots(key_ids)] = values

    def clear(self, key_ids: np.ndarray) -> None:
        self._values[self._store.slots(key_ids)] = self.desc.default

    def snapshot(self) -> Dict[str, Any]:
        return {"values": self._values.copy()}

    def restore(self, snap: Dict[str, Any], slot_remap=None) -> None:
        vals = snap["values"]
        if slot_remap is not None:
            self._values[slot_remap[1]] = vals[slot_remap[0]]
        else:
            self._values[: len(vals)] = vals


class ReducingState(ValueState):
    def __init__(self, store, desc: ReducingStateDescriptor):
        super().__init__(store, ValueStateDescriptor(
            desc.name, desc.dtype, desc.default))
        self.reduce = desc.reduce if desc.reduce is not None else np.add

    def add(self, key_ids: np.ndarray, values) -> None:
        """Fold a batch in with one scatter (``ufunc.at`` handles duplicate
        keys within the batch in order)."""
        slots = self._store.slots(key_ids)
        self.reduce.at(self._values, slots, values)


class ListState:
    """Append-log per key; host-resident (variable size never hits HBM)."""

    def __init__(self, store: "KeyedStateStore", desc: ListStateDescriptor):
        self.desc = desc
        self._lists: Dict[int, list] = {}

    def add(self, key_ids: np.ndarray, values) -> None:
        lists = self._lists
        vals = np.asarray(values)
        for k, v in zip(np.asarray(key_ids).tolist(), vals.tolist()):
            lists.setdefault(k, []).append(v)

    def get(self, key_id: int) -> list:
        return self._lists.get(int(key_id), [])

    def clear(self, key_ids) -> None:
        for k in np.atleast_1d(np.asarray(key_ids)).tolist():
            self._lists.pop(int(k), None)

    def keys(self) -> List[int]:
        return list(self._lists)

    def snapshot(self):
        return {"lists": {k: list(v) for k, v in self._lists.items()}}

    def restore(self, snap, slot_remap=None):
        self._lists = {int(k): list(v) for k, v in snap["lists"].items()}


class MapState:
    """Per-key hash map; host-resident."""

    def __init__(self, store: "KeyedStateStore", desc: MapStateDescriptor):
        self.desc = desc
        self._maps: Dict[int, dict] = {}

    def put(self, key_id: int, map_key, value) -> None:
        self._maps.setdefault(int(key_id), {})[map_key] = value

    def get(self, key_id: int, map_key, default=None):
        return self._maps.get(int(key_id), {}).get(map_key, default)

    def contains(self, key_id: int, map_key) -> bool:
        return map_key in self._maps.get(int(key_id), {})

    def remove(self, key_id: int, map_key) -> None:
        self._maps.get(int(key_id), {}).pop(map_key, None)

    def entries(self, key_id: int) -> dict:
        return self._maps.get(int(key_id), {})

    def clear(self, key_ids) -> None:
        for k in np.atleast_1d(np.asarray(key_ids)).tolist():
            self._maps.pop(int(k), None)

    def snapshot(self):
        return {"maps": {k: dict(v) for k, v in self._maps.items()}}

    def restore(self, snap, slot_remap=None):
        self._maps = {int(k): dict(v) for k, v in snap["maps"].items()}


_STATE_TYPES = {
    ValueStateDescriptor: ValueState,
    ReducingStateDescriptor: ReducingState,
    ListStateDescriptor: ListState,
    MapStateDescriptor: MapState,
}


class KeyedStateStore:
    """All keyed states of one operator, sharing one key -> slot index.

    reference: AbstractKeyedStateBackend.java keeps a map of registered
    states per name; state is addressed (key, namespace, name).
    """

    def __init__(self, capacity: int = 1 << 12):
        self._states: Dict[str, Any] = {}
        self._index = make_slot_index(capacity, on_grow=self._on_grow)
        self.capacity = self._index.capacity
        # states are registered lazily (first ctx.state(desc) call), which
        # can happen after restore — park unclaimed snapshots until then
        self._pending: Dict[str, Any] = {}
        self._pending_remap = None

    def _on_grow(self, old: int, new: int) -> None:
        self.capacity = new
        for st in self._states.values():
            if isinstance(st, ValueState):
                st._on_grow(old, new)

    def slots(self, key_ids: np.ndarray) -> np.ndarray:
        kid = np.asarray(key_ids, dtype=np.int64)
        return self._index.lookup_or_insert(
            kid, np.full(len(kid), _NS, dtype=np.int64))

    def get_state(self, desc):
        st = self._states.get(desc.name)
        if st is None:
            st = _STATE_TYPES[type(desc)](self, desc)
            self._states[desc.name] = st
            if desc.name in self._pending:
                st.restore(self._pending.pop(desc.name),
                           slot_remap=self._pending_remap)
        return st

    def known_key_ids(self) -> np.ndarray:
        """All key ids with a slot (dense states) — for full-table scans."""
        used = self._index.used_slots()
        return self._index.slot_key[used]

    def snapshot(self) -> Dict[str, Any]:
        used = self._index.used_slots()
        states = {n: s.snapshot() for n, s in self._states.items()}
        # restored states never re-accessed since restore are still parked in
        # _pending — carry them forward so a restore -> checkpoint -> restore
        # cycle keeps them. Dense ("values") snapshots are indexed by the OLD
        # slot layout; re-home them onto the current layout first.
        for n, s in self._pending.items():
            if "values" in s and self._pending_remap is not None:
                old_slots, new_slots = self._pending_remap
                vals = np.asarray(s["values"])
                rehomed = np.zeros(self.capacity, dtype=vals.dtype)
                rehomed[new_slots] = vals[old_slots]
                s = {"values": rehomed}
            states.setdefault(n, s)
        return {
            "keys": self._index.slot_key[used].copy(),
            "slots": used.copy(),
            "states": states,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        keys = np.asarray(snap["keys"], dtype=np.int64)
        old_slots = np.asarray(snap["slots"])
        # re-insert keys (fresh slot assignment — rescale-safe), then remap
        # dense state rows old slot -> new slot
        new_slots = self.slots(keys)
        remap = (old_slots, new_slots)
        self._pending_remap = remap
        for name, s in snap["states"].items():
            st = self._states.get(name)
            if st is not None:
                st.restore(s, slot_remap=remap)
            else:
                self._pending[name] = s
