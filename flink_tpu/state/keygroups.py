"""Key groups — the unit of state partitioning and rescaling.

Re-implements the reference's key-group contract
(reference: flink-runtime/src/main/java/org/apache/flink/runtime/state/KeyGroupRangeAssignment.java:63,75-77,124-127):

- ``key_group(key) = murmur(hash(key)) % max_parallelism``
- operator subtask for a group: ``group * parallelism // max_parallelism``
- a subtask owns the contiguous range of groups mapping to its index

Everything is vectorized over int64 key identities: arbitrary keys are first
hashed to a stable 64-bit identity (``hash_keys_to_i64``), then the 32-bit
murmur finalizer spreads them over groups. Key groups double as the mesh
sharding axis on TPU: group -> device is exactly the reference's
group -> subtask formula with parallelism = mesh size.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np

DEFAULT_MAX_PARALLELISM = 128  # reference lower bound 1 << 7


def murmur_fmix32(h: np.ndarray) -> np.ndarray:
    """Vectorized MurmurHash3 32-bit finalizer (public-domain algorithm).

    Matches the avalanche step the reference applies to ``key.hashCode()``
    before the modulo (reference: MathUtils.murmurHash via
    KeyGroupRangeAssignment.java:75-77 semantics: spread then modulo).
    """
    h = np.asarray(h, dtype=np.uint32).copy()
    h ^= h >> np.uint32(16)
    h *= np.uint32(0x85EBCA6B)
    h ^= h >> np.uint32(13)
    h *= np.uint32(0xC2B2AE35)
    h ^= h >> np.uint32(16)
    return h


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 — stable 64-bit mixer for integer keys."""
    x = np.asarray(x, dtype=np.uint64).copy()
    x += np.uint64(0x9E3779B97F4A7C15)
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def _fnv1a_64_bytes(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def hash_keys_to_i64(values: np.ndarray) -> np.ndarray:
    """Stable (run-to-run, process-to-process) int64 identity for a key column.

    Integer keys pass through unchanged — they already are identities; the
    murmur spread happens at group assignment. Strings/objects get FNV-1a
    over their UTF-8 bytes (stability matters: snapshots store key ids and
    must restore across processes, like the reference's serialized keys).
    """
    values = np.asarray(values)
    if values.dtype.kind in "iu":
        return values.astype(np.int64, copy=False)
    if values.dtype.kind == "f":
        return values.view(np.int64) if values.dtype == np.float64 else \
            values.astype(np.float64).view(np.int64)
    if values.dtype.kind in "US":
        values = values.astype(object)
    out = np.empty(len(values), dtype=np.int64)
    for i, v in enumerate(values):
        data = v.encode("utf-8") if isinstance(v, str) else (
            v if isinstance(v, bytes) else repr(v).encode("utf-8"))
        out[i] = np.int64(np.uint64(_fnv1a_64_bytes(data)))
    return out


def assign_key_groups(key_ids: np.ndarray, max_parallelism: int) -> np.ndarray:
    """key id -> key group, vectorized.

    reference: KeyGroupRangeAssignment.java:63 assignToKeyGroup /
    :75-77 computeKeyGroupForKeyHash = murmurHash(hash) % maxParallelism.
    Key ids are first folded 64->32 bit, then murmur-finalized.
    """
    k = np.asarray(key_ids, dtype=np.int64)
    folded = (k ^ (k >> np.int64(32))).astype(np.uint32)
    spread = murmur_fmix32(folded)
    return (spread % np.uint32(max_parallelism)).astype(np.int32)


def key_group_to_operator_index(
    key_groups: np.ndarray, max_parallelism: int, parallelism: int
) -> np.ndarray:
    """group -> owning subtask/shard index.

    reference: KeyGroupRangeAssignment.java:124-127
    computeOperatorIndexForKeyGroup = keyGroupId * parallelism / maxParallelism.
    """
    g = np.asarray(key_groups, dtype=np.int64)
    return (g * parallelism // max_parallelism).astype(np.int32)


@dataclasses.dataclass(frozen=True)
class KeyGroupRange:
    """Inclusive [start, end] range of key groups owned by one subtask.

    reference: flink-runtime/.../state/KeyGroupRange.java semantics.
    """

    start: int
    end: int  # inclusive

    @property
    def num_key_groups(self) -> int:
        return self.end - self.start + 1

    def __contains__(self, group: int) -> bool:
        return self.start <= group <= self.end

    def intersect(self, other: "KeyGroupRange") -> "KeyGroupRange":
        return KeyGroupRange(max(self.start, other.start), min(self.end, other.end))

    @property
    def empty(self) -> bool:
        return self.end < self.start


def compute_key_group_range(
    max_parallelism: int, parallelism: int, operator_index: int
) -> KeyGroupRange:
    """The contiguous group range owned by subtask ``operator_index``.

    reference: KeyGroupRangeAssignment.java computeKeyGroupRangeForOperatorIndex.
    """
    start = (operator_index * max_parallelism + parallelism - 1) // parallelism
    end = ((operator_index + 1) * max_parallelism - 1) // parallelism
    return KeyGroupRange(start, end)


def all_ranges(max_parallelism: int, parallelism: int) -> List[KeyGroupRange]:
    return [compute_key_group_range(max_parallelism, parallelism, i)
            for i in range(parallelism)]


def shard_key_group_ranges(parallelism: int, max_parallelism: int,
                           key_group_range=None) -> List[tuple]:
    """GLOBAL ``(first, last)`` inclusive key groups owned by each of
    the ``parallelism`` mesh shards — the exact inverse of the routing
    formula in ``parallel.shuffle.shard_records`` (including the
    local-space remap a sub-range engine applies). This is the split
    shard-granular checkpoints key their units by: the unit a record's
    state lives in is the unit its shard owns, by construction."""
    if key_group_range is None:
        first, span = 0, int(max_parallelism)
    else:
        first = int(key_group_range[0])
        span = int(key_group_range[1]) - first + 1
    return [
        (first + r.start, first + r.end)
        for r in (compute_key_group_range(span, parallelism, p)
                  for p in range(parallelism))
    ]


def host_key_group_ranges(num_hosts: int, local_devices: int,
                          max_parallelism: int,
                          key_group_range=None) -> List[tuple]:
    """GLOBAL ``(first, last)`` inclusive key groups owned by each HOST
    of a ``num_hosts x local_devices`` pod mesh — the stable
    process -> key-group-range mapping (ROADMAP item 2). Host ``h``
    owns the union of its local shards' ranges, which is contiguous by
    construction (shard ranges are contiguous and host-major adjacent),
    so a lost HOST is exactly "lose ``local_devices`` shard units,
    restore them, replay one contiguous range"."""
    shard_ranges = shard_key_group_ranges(
        int(num_hosts) * int(local_devices), max_parallelism,
        key_group_range)
    L = int(local_devices)
    return [(shard_ranges[h * L][0], shard_ranges[h * L + L - 1][1])
            for h in range(int(num_hosts))]


def host_of_key_group(key_groups: np.ndarray, num_hosts: int,
                      local_devices: int, max_parallelism: int,
                      assignment: "KeyGroupAssignment" = None
                      ) -> np.ndarray:
    """key group -> owning host, vectorized: the shard formula composed
    with the host-major shard layout (``shard // local_devices``).

    ``assignment`` (optional): a live :class:`KeyGroupAssignment` — when
    the data plane has rebalanced hot ranges away from the contiguous
    layout, serving-side routing must follow the same table or lookups
    land on the wrong host."""
    if assignment is not None:
        shard = assignment.shard_of_groups(key_groups)
    else:
        shard = key_group_to_operator_index(
            key_groups, max_parallelism,
            int(num_hosts) * int(local_devices))
    return (np.asarray(shard, dtype=np.int64)
            // int(local_devices)).astype(np.int32)


@dataclasses.dataclass(frozen=True, eq=False)
class KeyGroupAssignment:
    """Explicit (possibly non-contiguous) shard -> key-group assignment.

    Generalizes the reference's contiguous ``KeyGroupRange`` ownership
    (one range per subtask, ``group * parallelism // max_parallelism``)
    to an arbitrary table so a controller can move HOT ranges between
    shards without changing parallelism. ``table[local_group]`` is the
    owning shard for global group ``first + local_group``.

    The default (:meth:`contiguous`) reproduces the routing formula in
    ``parallel.shuffle.shard_records`` bit-for-bit, so threading an
    assignment through the data plane is a no-op until a move happens.

    Frozen + ``eq=False``: the ndarray field would break the generated
    ``__eq__``; identity comparison is what engine code wants anyway.
    Treat the table as immutable — constructors copy, mutators return
    new instances.
    """

    first: int
    num_shards: int
    table: np.ndarray  # int32 [span]: local group -> shard

    def __post_init__(self):
        t = np.ascontiguousarray(self.table, dtype=np.int32)
        if t.ndim != 1 or len(t) == 0:
            raise ValueError("assignment table must be a non-empty 1-D array")
        if int(self.num_shards) <= 0:
            raise ValueError(f"num_shards must be positive, got {self.num_shards}")
        if t.min() < 0 or t.max() >= int(self.num_shards):
            raise ValueError(
                f"assignment table values must be in [0, {self.num_shards}), "
                f"got range [{t.min()}, {t.max()}]")
        object.__setattr__(self, "table", t)
        object.__setattr__(self, "first", int(self.first))
        object.__setattr__(self, "num_shards", int(self.num_shards))

    # ---- constructors -------------------------------------------------

    @classmethod
    def contiguous(cls, parallelism: int, max_parallelism: int,
                   key_group_range=None) -> "KeyGroupAssignment":
        """The default layout: identical to ``shard_records``'s formula
        (including the local-space remap a sub-range engine applies)."""
        if key_group_range is None:
            first, span = 0, int(max_parallelism)
        else:
            first = int(key_group_range[0])
            span = int(key_group_range[1]) - first + 1
        local = np.arange(span, dtype=np.int64)
        table = (local * int(parallelism) // span).astype(np.int32)
        return cls(first=first, num_shards=int(parallelism), table=table)

    def move(self, groups: Sequence, dst_shard: int) -> "KeyGroupAssignment":
        """New assignment with GLOBAL ``groups`` reassigned to ``dst_shard``."""
        g = np.asarray(groups, dtype=np.int64) - self.first
        if len(g) and (g.min() < 0 or g.max() >= len(self.table)):
            raise ValueError(f"groups out of range [{self.first}, "
                             f"{self.first + len(self.table) - 1}]")
        table = self.table.copy()
        table[g] = np.int32(dst_shard)
        return KeyGroupAssignment(self.first, self.num_shards, table)

    # ---- routing ------------------------------------------------------

    def shard_of_groups(self, key_groups: np.ndarray) -> np.ndarray:
        """GLOBAL key group -> owning shard (vectorized table lookup)."""
        g = np.asarray(key_groups, dtype=np.int64) - self.first
        return self.table[g]

    def shard_of_keys(self, key_ids: np.ndarray,
                      max_parallelism: int) -> np.ndarray:
        """key id -> owning shard: the murmur group spread composed with
        the assignment table (replaces the contiguous formula)."""
        return self.shard_of_groups(
            assign_key_groups(key_ids, max_parallelism))

    def groups_of_shard(self, shard: int) -> np.ndarray:
        """GLOBAL key groups owned by ``shard`` (ascending)."""
        return (np.nonzero(self.table == np.int32(shard))[0]
                + self.first).astype(np.int64)

    # ---- structure ----------------------------------------------------

    @property
    def span(self) -> int:
        return len(self.table)

    @property
    def is_contiguous(self) -> bool:
        """True iff the table equals the default contiguous layout."""
        local = np.arange(len(self.table), dtype=np.int64)
        expect = (local * self.num_shards // len(self.table)).astype(np.int32)
        return bool(np.array_equal(self.table, expect))

    def runs(self) -> List[tuple]:
        """Maximal GLOBAL ``(first, last, shard)`` same-shard runs in
        group order — the unit granularity for sharded checkpoints
        under a non-contiguous layout (one unit per run)."""
        t = self.table
        cuts = np.nonzero(t[1:] != t[:-1])[0] + 1
        starts = np.concatenate(([0], cuts))
        ends = np.concatenate((cuts - 1, [len(t) - 1]))
        return [(int(s) + self.first, int(e) + self.first, int(t[s]))
                for s, e in zip(starts, ends)]


def validate_max_parallelism(max_parallelism: int) -> None:
    if not (1 <= max_parallelism <= (1 << 15)):
        raise ValueError(
            f"max_parallelism must be in [1, 32768], got {max_parallelism}")
