"""Paged (cohort-granular) spill bookkeeping, shared across engines.

The paged layout (``spill_layout="pages"``) targets session-shaped state:
one row per namespace, millions of namespaces. Evicting namespace-by-
namespace would mean one spill entry per session; instead the unit of
movement is an EVICTION COHORT — the coldest rows of the device table,
however many sessions they span — stored as one page entry carrying its
own ``ns`` column (reference: RocksDB block granularity — state moves in
blocks, not per-key records).

This module owns the host bookkeeping every paged table needs:

- the (namespace -> page, row) membership map as lazily-sorted parallel
  arrays (binary-searched per batch, no per-session Python),
- LAZY TOMBSTONES: a reload extracts exactly the requested rows from
  their pages by row index (one ``take`` per page) and simply unmaps
  them — the pages' other rows are NOT rewritten. A row's liveness is
  its presence in the membership map; stale copies left behind in page
  storage are skipped by every reader (snapshots, queries) via the same
  map. This is what keeps reload write-amplification at zero: the old
  split-on-reload design re-bundled every unrequested sibling row into
  a fresh page, rewriting ~16x more rows than it reloaded at the
  session-thrashing benchmark shape. Accepted trade-off: a page that
  overflowed to the FILESYSTEM tier is re-read (``peek``) on each
  reload round that touches it until compaction/reap — reads are cheap
  and host-memory pages (the common case) peek for free, while the old
  design paid a guaranteed rewrite of every sibling row instead.
- THRESHOLD COMPACTION: once a page's dead fraction (tombstoned rows /
  total rows) crosses ``compact_dead_fraction``, the page is rewritten
  with only its live rows (``rows_compacted`` counts them) and the dead
  space is reclaimed — the RocksDB compaction analogy: deletes are
  logical tombstones first, physical space comes back in batched
  background rewrites, never on the read path. A page whose rows all
  die is dropped outright (no rewrite at all).
- spill traffic counters (pages/rows evicted and reloaded, rows split
  on reload — now structurally ~0 — and rows compacted) for benchmarks
  and observability.

The single-device ``SlotTable`` uses one ``PagedSpillMap``; the
mesh-sharded session engine keeps one per shard (keys never migrate
between shards, so spilled pages are shard-local like the device rows).
Device-side data movement (gather/reset on evict, put on reload) stays
with the owning engine — flat kernels on one device, ``shard_map``
programs on the mesh.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.chaos import injection as chaos

COUNTER_NAMES = ("pages_evicted", "pages_reloaded", "rows_evicted",
                 "rows_reloaded", "rows_split_on_reload", "rows_compacted")


def sorted_match(sorted_vals: np.ndarray, queries: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Membership probe against a sorted array: ``(mask, pos)`` where
    ``mask[i]`` says ``queries[i]`` occurs in ``sorted_vals`` and
    ``pos[i]`` is its (clamped) index — positions are only meaningful
    where ``mask`` holds. The one implementation of the
    searchsorted-clamp-compare idiom every membership check here uses."""
    queries = np.asarray(queries)
    if not len(sorted_vals):
        return (np.zeros(len(queries), dtype=bool),
                np.zeros(len(queries), dtype=np.int64))
    pos = np.minimum(np.searchsorted(sorted_vals, queries),
                     len(sorted_vals) - 1)
    return sorted_vals[pos] == queries, pos

#: rewrite a page once more than this fraction of its rows are dead
#: (0.5: a page is compacted at most O(log rows) times over its life,
#: so compaction traffic is amortized-constant per row — the same
#: geometric argument as LSM compaction fan-out)
COMPACT_DEAD_FRACTION = 0.5


class PagedSpillMap:
    """Membership + lifecycle bookkeeping for one paged spill tier."""

    def __init__(self,
                 compact_dead_fraction: float = COMPACT_DEAD_FRACTION
                 ) -> None:
        #: spilled (ns -> page, row-within-page) mapping as parallel
        #: arrays; kept sorted by ns lazily (evictions append, reloads
        #: tombstone). ``sp_row`` is stable: pages are immutable once
        #: written — compaction assigns fresh row indexes.
        self.sp_ns = np.empty(0, dtype=np.int64)
        self.sp_page = np.empty(0, dtype=np.int64)
        self.sp_row = np.empty(0, dtype=np.int64)
        #: map-entry tombstones: an unmap only FLAGS its entries dead
        #: (O(extracted)); dead entries purge in bulk at the next
        #: sort()/compress cycle. Compressing the parallel arrays on
        #: every unmap cost three O(map) copies per extraction round —
        #: at the session-thrashing shape that was the single largest
        #: spill-bookkeeping term.
        self.sp_dead = np.empty(0, dtype=bool)
        self._dead_count = 0
        self.sorted = True
        self.compact_dead_fraction = float(compact_dead_fraction)
        #: latency tier: when set, the fire-path extraction QUEUES its
        #: touched pages here instead of sweeping (reap/compact) them
        #: inline — space reclamation is time-insensitive, so the owner
        #: drains the queue on its next ingest step
        #: (run_deferred_sweeps) and the fire span stays a bounded
        #: delta instead of absorbing compaction bursts
        self.defer_sweeps = False
        self.deferred_pages: set = set()
        #: per-page physical row count (as stored) and live row count
        #: (still mapped); dead fraction = 1 - live/rows
        self.page_rows: Dict[int, int] = {}
        self.page_live: Dict[int, int] = {}
        self.next_page = 1
        self.pages_evicted = 0
        self.pages_reloaded = 0
        self.rows_evicted = 0
        self.rows_reloaded = 0
        self.rows_split_on_reload = 0
        self.rows_compacted = 0

    def __len__(self) -> int:
        return len(self.sp_ns) - self._dead_count

    def counters(self) -> Dict[str, int]:
        return {name: int(getattr(self, name)) for name in COUNTER_NAMES}

    @staticmethod
    def zero_counters() -> Dict[str, int]:
        return {name: 0 for name in COUNTER_NAMES}

    # ------------------------------------------------------------ membership

    def _compress(self, keep: np.ndarray) -> None:
        self.sp_ns = self.sp_ns[keep]
        self.sp_page = self.sp_page[keep]
        self.sp_row = self.sp_row[keep]
        self.sp_dead = self.sp_dead[keep]
        self._dead_count = int(self.sp_dead.sum())

    def sort(self) -> None:
        """Settle the map for reads: purge dead entries when appends
        arrived (the at-most-one-entry-per-ns invariant the searchsorted
        probes rely on) or when tombstones dominate, then re-sort."""
        if not self.sorted:
            if self._dead_count:
                self._compress(~self.sp_dead)
            o = np.argsort(self.sp_ns, kind="stable")
            self.sp_ns = self.sp_ns[o]
            self.sp_page = self.sp_page[o]
            self.sp_row = self.sp_row[o]
            self.sp_dead = self.sp_dead[o]
            self.sorted = True
        elif self._dead_count * 2 > len(self.sp_ns):
            # bound tombstone memory; a mask compress keeps sort order
            self._compress(~self.sp_dead)

    def spilled_mask(self, nss: np.ndarray) -> np.ndarray:
        """Vectorized membership: which of ``nss`` are spilled."""
        self.sort()
        mask, pos = sorted_match(self.sp_ns, nss)
        if self._dead_count:
            mask &= ~self.sp_dead[pos]
        return mask

    def positions_for(self, nss: np.ndarray) -> np.ndarray:
        """Map-array positions of the spilled members of ``nss``."""
        self.sort()
        mask, pos = sorted_match(
            self.sp_ns, np.unique(np.asarray(nss, dtype=np.int64)))
        if self._dead_count:
            mask &= ~self.sp_dead[pos]
        return pos[mask]

    def page_of(self, ns: int) -> Optional[int]:
        """The page holding ``ns``, or None (read-only point probe)."""
        if not len(self.sp_ns):
            return None
        self.sort()
        p = int(np.searchsorted(self.sp_ns, int(ns)))
        if p >= len(self.sp_ns) or int(self.sp_ns[p]) != int(ns) \
                or bool(self.sp_dead[p]):
            return None
        return int(self.sp_page[p])

    def live_ns(self) -> np.ndarray:
        """The live (non-tombstoned) spilled namespaces — the listing
        external readers use instead of the raw ``sp_ns`` array."""
        self.sort()
        if self._dead_count:
            return self.sp_ns[~self.sp_dead]
        return self.sp_ns

    def live_row_mask(self, page: int, rns: np.ndarray) -> np.ndarray:
        """Which rows of a stored page entry are still live: a row is
        live iff its namespace is still mapped to THIS page (reloaded
        and freed rows are tombstones — physically present, logically
        gone). Readers (snapshots, queries) filter through this."""
        rns = np.asarray(rns, dtype=np.int64)
        self.sort()
        # re-check AFTER sort: a fully-tombstoned map compresses to
        # empty there (common with deferred fire-path sweeps)
        if not len(self.sp_ns):
            return np.zeros(len(rns), dtype=bool)
        mask, pos = sorted_match(self.sp_ns, rns)
        if self._dead_count:
            mask &= ~self.sp_dead[pos]
        return mask & (self.sp_page[pos] == int(page))

    def record(self, nss: np.ndarray, page: int) -> None:
        n = len(nss)
        self.sp_ns = np.concatenate([self.sp_ns, nss])
        self.sp_page = np.concatenate([
            self.sp_page, np.full(n, page, dtype=np.int64)])
        self.sp_row = np.concatenate([
            self.sp_row, np.arange(n, dtype=np.int64)])
        self.sp_dead = np.concatenate([
            self.sp_dead, np.zeros(n, dtype=bool)])
        self.page_rows[int(page)] = n
        self.page_live[int(page)] = n
        self.sorted = False

    def unmap_positions(self, pos: np.ndarray) -> List[int]:
        """Tombstone the map entries at ``pos``; returns the distinct
        pages they lived in (candidates for reap/compact). O(len(pos)):
        the arrays are not compressed here — dead entries purge in bulk
        at the next sort cycle."""
        if not len(pos):
            return []
        pages, counts = np.unique(self.sp_page[pos], return_counts=True)
        for page, c in zip(pages.tolist(), counts.tolist()):
            self.page_live[page] = self.page_live.get(page, c) - c
        self.sp_dead[pos] = True
        self._dead_count += len(pos)
        return pages.tolist()

    def remove_pages(self, pages: np.ndarray) -> None:
        self._compress(~np.isin(self.sp_page, pages))
        for page in np.asarray(pages).tolist():
            self.page_rows.pop(int(page), None)
            self.page_live.pop(int(page), None)

    def clear(self) -> None:
        self.sp_ns = np.empty(0, dtype=np.int64)
        self.sp_page = np.empty(0, dtype=np.int64)
        self.sp_row = np.empty(0, dtype=np.int64)
        self.sp_dead = np.empty(0, dtype=bool)
        self._dead_count = 0
        self.sorted = True
        self.page_rows.clear()
        self.page_live.clear()


def spill_page(spill, pmap: PagedSpillMap, entry: Dict[str, np.ndarray],
               count: bool = True) -> int:
    """Store one eviction cohort as a page entry; returns the page id.

    ``entry`` carries ``key_id`` / ``ns`` / ``dirty`` / ``leaf_i``
    columns. ``count=False`` for internal rewrites and restore, which
    are not evictions.
    """
    page = pmap.next_page
    pmap.next_page += 1
    spill.put(page, entry, dirty=bool(entry["dirty"].any()))
    pmap.record(np.asarray(entry["ns"], dtype=np.int64), page)
    if count:
        pmap.pages_evicted += 1
        pmap.rows_evicted += len(entry["ns"])
    return page


def _sweep_pages(spill, pmap: PagedSpillMap, pages: Sequence[int]) -> None:
    """Reclaim dead space in the touched pages: a fully-dead page drops
    outright; a page whose dead fraction crossed the threshold is
    rewritten with only its live rows (``rows_compacted``). Everything
    else keeps its tombstones — no read-path rewrites (the RocksDB
    compaction discipline)."""
    for page in pages:
        page = int(page)
        total = pmap.page_rows.get(page)
        if total is None:
            continue
        live = pmap.page_live.get(page, 0)
        if live <= 0:
            # delete without load: a fully-dead fs page is unlinked,
            # not read back just to be thrown away
            spill.discard(page)
            pmap.page_rows.pop(page, None)
            pmap.page_live.pop(page, None)
            continue
        if (total - live) / total <= pmap.compact_dead_fraction:
            continue
        _compact_page(spill, pmap, page)


def _compact_page(spill, pmap: PagedSpillMap, page: int) -> None:
    """Rewrite one page with only its live rows; remaps its membership
    entries to the fresh page in place."""
    if chaos.armed():
        # a failed compaction is SAFE to skip: tombstones stay valid
        # and the page re-qualifies next sweep (the RocksDB analogy —
        # a lost compaction costs space, never correctness). Only a
        # recoverable injected fault defers; a hard one crashes here,
        # BEFORE the pop, so no page is half-moved.
        try:
            chaos.fault_point("spill.page_compact", page=page)
        except chaos.InjectedFault as f:
            if f.recoverable:
                c = chaos.controller()
                if c is not None:
                    c.note_recovery()
                return
            raise
    entry = spill.pop(page)
    pmap.page_rows.pop(page, None)
    pmap.page_live.pop(page, None)
    if entry is None:
        return
    was_dirty = bool(entry.get("__was_dirty__", False))
    pos = np.nonzero((pmap.sp_page == page) & ~pmap.sp_dead)[0]
    if not len(pos):
        return
    old_rows = pmap.sp_row[pos]
    order = np.argsort(old_rows)  # preserve storage order
    pos, old_rows = pos[order], old_rows[order]
    new_entry = {
        k: np.asarray(v)[old_rows] for k, v in entry.items()
        if k != "__was_dirty__"
    }
    if not was_dirty:
        # the tier-level flag was cleared by a snapshot since this page
        # spilled, so its rows HAVE been shipped — carrying the stale
        # per-row dirty column forward would re-ship the unchanged rows
        # in every later delta
        new_entry["dirty"] = np.zeros(len(old_rows), dtype=bool)
    new_page = pmap.next_page
    pmap.next_page += 1
    spill.put(new_page, new_entry,
              dirty=was_dirty and bool(new_entry["dirty"].any()))
    n = len(pos)
    pmap.sp_page[pos] = new_page
    pmap.sp_row[pos] = np.arange(n, dtype=np.int64)
    pmap.page_rows[new_page] = n
    pmap.page_live[new_page] = n
    pmap.rows_compacted += n


def _peek_page(spill, page: int):
    """One page read on the reload path. Under chaos, a transient
    injected reload failure retries with restart-strategy backoff in
    place (the I/O-retry contract shared with checkpoint storage); a
    persistent one propagates as the engine crash it would be."""
    if not chaos.armed():
        return spill.peek(page)

    def attempt():
        chaos.fault_point("spill.page_reload", page=page)
        return spill.peek(page)

    return chaos.run_recoverable("spill.page_reload", attempt)


def read_spilled_rows(spill, pmap: Optional[PagedSpillMap], paged: bool,
                      wants, on_row) -> None:
    """Serving-path cold read: resolve ``wants`` — an iterable of
    ``(tag, key_id, ns)`` — against the tier, grouping by tier entry so
    each page is peeked (and, for the fs tier, loaded from disk) ONCE
    per batch, however many of the batch's rows it holds. Calls
    ``on_row(tag, entry, src_row)`` for each row found. Read-only: no
    residency change, no membership mutation. The ONE copy of the
    miss-scan for both layouts — ``SlotTable.query_batch_pairs`` and
    ``MeshSessionEngine.query_batch`` read through it."""
    by_entry: Dict[int, list] = {}
    for tag, key_id, ns in wants:
        ek = (pmap.page_of(int(ns)) if paged
              else (int(ns) if int(ns) in spill else None))
        if ek is not None:
            by_entry.setdefault(int(ek), []).append(
                (tag, int(key_id), int(ns)))
    for ek, rows in by_entry.items():
        entry = spill.peek(ek)
        if entry is None:
            continue
        entry_keys = np.asarray(entry["key_id"], dtype=np.int64)
        entry_ns = (np.asarray(entry["ns"], dtype=np.int64)
                    if paged else None)
        for tag, key_id, ns in rows:
            if paged:
                pos = np.nonzero((entry_keys == key_id)
                                 & (entry_ns == ns))[0]
            else:
                pos = np.nonzero(entry_keys == key_id)[0]
            if len(pos):
                on_row(tag, entry, int(pos[0]))


def reload_rows_for(spill, pmap: PagedSpillMap, nss: np.ndarray,
                    leaf_dtypes: Sequence) -> Optional[
                        Tuple[np.ndarray, np.ndarray, np.ndarray,
                              List[np.ndarray]]]:
    """Extract the REQUESTED rows (and only them) from their pages;
    return ``(keys, rns, dirty, leaf_values)`` for the caller's device
    put, or None when nothing relevant was spilled.

    Amplification-free: each touched page is read once and the rows are
    pulled by stored row index (one ``take`` per page); the pages'
    other rows stay exactly where they are, and the reloaded rows
    become lazy tombstones (unmapped, physically still in the page).
    Space comes back when a page's dead fraction crosses the compaction
    threshold — never by rewriting cohort remainders on the reload
    path, which cost ~16x the reloaded rows in pure host repacking at
    the session-thrashing shape."""
    nss = np.asarray(nss, dtype=np.int64)
    pos = pmap.positions_for(nss)
    if not len(pos):
        return None
    hit_pages = pmap.sp_page[pos]
    hit_rows = pmap.sp_row[pos]
    order = np.argsort(hit_pages, kind="stable")
    hit_pages, hit_rows = hit_pages[order], hit_rows[order]
    bounds = np.nonzero(np.diff(hit_pages))[0] + 1
    starts = np.concatenate(([0], bounds))
    ends = np.concatenate((bounds, [len(hit_pages)]))
    key_chunks, ns_chunks, dirty_chunks = [], [], []
    leaf_chunks: List[List[np.ndarray]] = [[] for _ in leaf_dtypes]
    pages_read = 0
    for a, b in zip(starts.tolist(), ends.tolist()):
        page = int(hit_pages[a])
        entry = _peek_page(spill, page)
        if entry is None:
            continue
        pages_read += 1
        rows = hit_rows[a:b]
        key_chunks.append(
            np.asarray(entry["key_id"], dtype=np.int64)[rows])
        ns_chunks.append(np.asarray(entry["ns"], dtype=np.int64)[rows])
        dirty_chunks.append(
            np.asarray(entry["dirty"], dtype=bool)[rows])
        for i, dt in enumerate(leaf_dtypes):
            leaf_chunks[i].append(
                np.asarray(entry[f"leaf_{i}"], dtype=dt)[rows])
    touched = pmap.unmap_positions(pos)
    if pmap.defer_sweeps:
        pmap.deferred_pages.update(touched)
    else:
        _sweep_pages(spill, pmap, touched)
    if not key_chunks:
        return None
    keys = np.concatenate(key_chunks)
    rns = np.concatenate(ns_chunks)
    dirty = np.concatenate(dirty_chunks)
    vals = [np.concatenate(c) for c in leaf_chunks]
    pmap.pages_reloaded += pages_read
    pmap.rows_reloaded += len(keys)
    return keys, rns, dirty, vals


def drop_spilled_sessions(spill, pmap: PagedSpillMap,
                          nss: np.ndarray) -> None:
    """Tombstone spilled sessions that were freed (rare: fires reload
    first); fully-dead pages are reaped and mostly-dead pages compact,
    so their storage cannot leak for the rest of the run."""
    if not len(pmap.sp_ns):
        return
    pos = pmap.positions_for(np.asarray(nss, dtype=np.int64))
    if not len(pos):
        return
    touched = pmap.unmap_positions(pos)
    if pmap.defer_sweeps:
        pmap.deferred_pages.update(touched)
    else:
        _sweep_pages(spill, pmap, touched)


def run_deferred_sweeps(spill, pmap: PagedSpillMap) -> int:
    """Drain the pages queued by fire-path extractions under
    ``defer_sweeps`` — reap the fully-dead ones, compact the mostly-dead
    ones. Called by the owning engine on its INGEST step (and harmless
    to skip: tombstones stay valid, only space reclamation is delayed).
    Returns pages swept."""
    if not pmap.deferred_pages:
        return 0
    pages = sorted(pmap.deferred_pages)
    pmap.deferred_pages.clear()
    _sweep_pages(spill, pmap, pages)
    return len(pages)


def restore_into_pages(spill, pmap: PagedSpillMap, key_ids: np.ndarray,
                       namespaces: np.ndarray, leaves: List[np.ndarray],
                       page_rows: int,
                       dirty: Optional[np.ndarray] = None,
                       append: bool = False) -> None:
    """Pack restored logical rows into page-sized spill entries (sorted
    by ns, never splitting one namespace across pages) — a snapshot far
    larger than the device budget restores with bounded device memory
    and reloads lazily by page. Clears any stale pages first
    (re-restore).

    ``dirty``: optional per-row dirtiness to carry into the pages — the
    live-rescale handoff re-homes rows that have NOT been checkpointed
    since they changed, and the next delta snapshot must still ship
    them. A checkpoint restore passes None (restored state is the new
    incremental base, nothing is dirty).

    ``append=True`` keeps the tier's existing pages (partial failover:
    a lost shard's key groups restore INTO survivors whose own pages
    must stay intact). The caller guarantees the appended namespaces
    are not already mapped — true by construction for the shard-loss
    path, whose restored rows belong to key groups the surviving tiers
    never held."""
    if not append:
        if len(pmap.sp_ns):
            for page in np.unique(pmap.sp_page).tolist():
                spill.discard(int(page))
        pmap.clear()
    order = np.argsort(namespaces, kind="stable")
    s_ns = namespaces[order]
    s_keys = key_ids[order]
    s_leaves = [l[order] for l in leaves]
    s_dirty = (np.asarray(dirty, dtype=bool)[order]
               if dirty is not None else None)
    total = len(s_ns)
    a = 0
    while a < total:
        b = min(a + page_rows, total)
        while b < total and s_ns[b] == s_ns[b - 1]:
            b += 1
        entry = {"key_id": s_keys[a:b], "ns": s_ns[a:b],
                 "dirty": (s_dirty[a:b] if s_dirty is not None
                           else np.zeros(b - a, dtype=bool)),
                 **{f"leaf_{i}": s_leaves[i][a:b]
                    for i in range(len(s_leaves))}}
        spill_page(spill, pmap, entry, count=False)
        a = b
    if not append:
        # pages were appended in ascending-ns order: the map is sorted
        pmap.sorted = True
