"""Paged (cohort-granular) spill bookkeeping, shared across engines.

The paged layout (``spill_layout="pages"``) targets session-shaped state:
one row per namespace, millions of namespaces. Evicting namespace-by-
namespace would mean one spill entry per session; instead the unit of
movement is an EVICTION COHORT — the coldest rows of the device table,
however many sessions they span — stored as one page entry carrying its
own ``ns`` column (reference: RocksDB block granularity — state moves in
blocks, not per-key records).

This module owns the host bookkeeping every paged table needs:

- the (namespace -> page) membership map as lazily-sorted parallel
  arrays (binary-searched per batch, no per-session Python),
- the dead-spilled set (sessions freed while spilled; their rows are
  dropped on reload/snapshot and their empty pages reaped),
- split-on-reload: a reload pops whole pages but only the REQUESTED
  rows go back to the device; the pages' other rows re-bundle into a
  fresh page host-side, so page churn cannot read-amplify past the
  device budget,
- spill traffic counters (pages/rows evicted and reloaded, rows split
  on reload) for benchmarks and observability.

The single-device ``SlotTable`` uses one ``PagedSpillMap``; the
mesh-sharded session engine keeps one per shard (keys never migrate
between shards, so spilled pages are shard-local like the device rows).
Device-side data movement (gather/reset on evict, put on reload) stays
with the owning engine — flat kernels on one device, ``shard_map``
programs on the mesh.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

COUNTER_NAMES = ("pages_evicted", "pages_reloaded", "rows_evicted",
                 "rows_reloaded", "rows_split_on_reload")


class PagedSpillMap:
    """Membership + lifecycle bookkeeping for one paged spill tier."""

    def __init__(self) -> None:
        #: spilled (ns -> page) mapping as parallel arrays; kept sorted
        #: by ns lazily (evictions append, reloads filter)
        self.sp_ns = np.empty(0, dtype=np.int64)
        self.sp_page = np.empty(0, dtype=np.int64)
        self.sorted = True
        #: sessions freed while spilled (rare: fires reload first) —
        #: their page rows are dropped on reload/snapshot
        self.dead: set = set()
        self.next_page = 1
        self.pages_evicted = 0
        self.pages_reloaded = 0
        self.rows_evicted = 0
        self.rows_reloaded = 0
        self.rows_split_on_reload = 0

    def __len__(self) -> int:
        return len(self.sp_ns)

    def counters(self) -> Dict[str, int]:
        return {name: int(getattr(self, name)) for name in COUNTER_NAMES}

    @staticmethod
    def zero_counters() -> Dict[str, int]:
        return {name: 0 for name in COUNTER_NAMES}

    # ------------------------------------------------------------ membership

    def sort(self) -> None:
        if not self.sorted:
            o = np.argsort(self.sp_ns, kind="stable")
            self.sp_ns = self.sp_ns[o]
            self.sp_page = self.sp_page[o]
            self.sorted = True

    def spilled_mask(self, nss: np.ndarray) -> np.ndarray:
        """Vectorized membership: which of ``nss`` are spilled."""
        if not len(self.sp_ns):
            return np.zeros(len(nss), dtype=bool)
        self.sort()
        pos = np.searchsorted(self.sp_ns, nss)
        pos = np.minimum(pos, len(self.sp_ns) - 1)
        return self.sp_ns[pos] == nss

    def pages_for(self, nss: np.ndarray) -> np.ndarray:
        """Unique page ids containing any of ``nss``."""
        if not len(self.sp_ns):
            return np.empty(0, dtype=np.int64)
        self.sort()
        nss = np.asarray(nss, dtype=np.int64)
        pos = np.searchsorted(self.sp_ns, nss)
        pos = np.minimum(pos, len(self.sp_ns) - 1)
        hit = self.sp_ns[pos] == nss
        if not hit.any():
            return np.empty(0, dtype=np.int64)
        return np.unique(self.sp_page[pos[hit]])

    def page_of(self, ns: int) -> Optional[int]:
        """The page holding ``ns``, or None (read-only point probe)."""
        if not len(self.sp_ns):
            return None
        self.sort()
        p = int(np.searchsorted(self.sp_ns, int(ns)))
        if p >= len(self.sp_ns) or int(self.sp_ns[p]) != int(ns):
            return None
        return int(self.sp_page[p])

    def record(self, nss: np.ndarray, page: int) -> None:
        self.sp_ns = np.concatenate([self.sp_ns, nss])
        self.sp_page = np.concatenate([
            self.sp_page, np.full(len(nss), page, dtype=np.int64)])
        self.sorted = False

    def remove_pages(self, pages: np.ndarray) -> None:
        keep = ~np.isin(self.sp_page, pages)
        self.sp_ns = self.sp_ns[keep]
        self.sp_page = self.sp_page[keep]

    def clear(self) -> None:
        self.sp_ns = np.empty(0, dtype=np.int64)
        self.sp_page = np.empty(0, dtype=np.int64)
        self.sorted = True
        self.dead.clear()


def spill_page(spill, pmap: PagedSpillMap, entry: Dict[str, np.ndarray],
               count: bool = True) -> int:
    """Store one eviction cohort as a page entry; returns the page id.

    ``entry`` carries ``key_id`` / ``ns`` / ``dirty`` / ``leaf_i``
    columns. ``count=False`` for internal re-bundling and restore, which
    are not evictions.
    """
    page = pmap.next_page
    pmap.next_page += 1
    spill.put(page, entry, dirty=bool(entry["dirty"].any()))
    pmap.record(np.asarray(entry["ns"], dtype=np.int64), page)
    if count:
        pmap.pages_evicted += 1
        pmap.rows_evicted += len(entry["ns"])
    return page


def reload_rows_for(spill, pmap: PagedSpillMap, nss: np.ndarray,
                    leaf_dtypes: Sequence) -> Optional[
                        Tuple[np.ndarray, np.ndarray, np.ndarray,
                              List[np.ndarray]]]:
    """Pop every page containing any of ``nss``; return the requested
    rows as ``(keys, rns, dirty, leaf_values)`` for the caller's device
    put, or None when nothing relevant was spilled.

    Only the REQUESTED rows leave; the popped pages' other rows
    re-bundle into a fresh page host-side (pure NumPy — no device
    traffic). Without this split, page churn mixes cohorts over time and
    a fire's reload would drag in whole pages of not-yet-due sessions,
    read-amplifying past the device budget. Dead rows (sessions freed
    while spilled) are dropped here.
    """
    nss = np.asarray(nss, dtype=np.int64)
    pages = pmap.pages_for(nss)
    if not len(pages):
        return None
    key_chunks, ns_chunks, dirty_chunks = [], [], []
    leaf_chunks: List[List[np.ndarray]] = [[] for _ in leaf_dtypes]
    for page in pages.tolist():
        entry = spill.pop(int(page))
        if entry is None:
            continue
        key_chunks.append(np.asarray(entry["key_id"], dtype=np.int64))
        ns_chunks.append(np.asarray(entry["ns"], dtype=np.int64))
        dirty_chunks.append(np.asarray(entry["dirty"], dtype=bool))
        for i, dt in enumerate(leaf_dtypes):
            leaf_chunks[i].append(np.asarray(entry[f"leaf_{i}"], dtype=dt))
    if not key_chunks:
        return None
    keys = np.concatenate(key_chunks)
    rns = np.concatenate(ns_chunks)
    dirty = np.concatenate(dirty_chunks)
    vals = [np.concatenate(c) for c in leaf_chunks]
    if pmap.dead:
        dead = np.asarray(sorted(pmap.dead), dtype=np.int64)
        alive = ~np.isin(rns, dead)
        if not alive.all():
            gone = rns[~alive]
            pmap.dead.difference_update(gone.tolist())
            keys, rns, dirty = keys[alive], rns[alive], dirty[alive]
            vals = [v[alive] for v in vals]
    pmap.remove_pages(pages)
    pmap.pages_reloaded += len(pages)
    want = np.isin(rns, np.unique(nss))
    rest = ~want
    if rest.any():
        r_entry = {"key_id": keys[rest], "ns": rns[rest],
                   "dirty": dirty[rest],
                   **{f"leaf_{i}": v[rest] for i, v in enumerate(vals)}}
        spill_page(spill, pmap, r_entry, count=False)
        pmap.rows_split_on_reload += int(rest.sum())
        keys, rns, dirty = keys[want], rns[want], dirty[want]
        vals = [v[want] for v in vals]
    if len(keys) == 0:
        return None
    pmap.rows_reloaded += len(keys)
    return keys, rns, dirty, vals


def drop_spilled_sessions(spill, pmap: PagedSpillMap,
                          nss: np.ndarray) -> None:
    """Mark spilled sessions dead; reap pages left with no live mapping
    entries (they could never reload — their storage and dead-set
    entries would otherwise leak for the rest of the run)."""
    if not len(pmap.sp_ns):
        return
    nss = np.asarray(nss, dtype=np.int64)
    dead = nss[pmap.spilled_mask(nss)]
    if not len(dead):
        return
    pmap.dead.update(dead.tolist())
    kill = np.isin(pmap.sp_ns, dead)
    dead_pages = np.unique(pmap.sp_page[kill])
    keep = ~kill
    pmap.sp_ns = pmap.sp_ns[keep]
    pmap.sp_page = pmap.sp_page[keep]
    gone = dead_pages[~np.isin(dead_pages, np.unique(pmap.sp_page))]
    for page in gone.tolist():
        entry = spill.pop(int(page))
        if entry is not None:
            pmap.dead.difference_update(
                np.asarray(entry["ns"], dtype=np.int64).tolist())


def restore_into_pages(spill, pmap: PagedSpillMap, key_ids: np.ndarray,
                       namespaces: np.ndarray, leaves: List[np.ndarray],
                       page_rows: int) -> None:
    """Pack restored logical rows into page-sized spill entries (sorted
    by ns, never splitting one namespace across pages) — a snapshot far
    larger than the device budget restores with bounded device memory
    and reloads lazily by page. Clears any stale pages first
    (re-restore)."""
    if len(pmap.sp_ns):
        for page in np.unique(pmap.sp_page).tolist():
            spill.drop(int(page))
    pmap.clear()
    order = np.argsort(namespaces, kind="stable")
    s_ns = namespaces[order]
    s_keys = key_ids[order]
    s_leaves = [l[order] for l in leaves]
    total = len(s_ns)
    a = 0
    while a < total:
        b = min(a + page_rows, total)
        while b < total and s_ns[b] == s_ns[b - 1]:
            b += 1
        entry = {"key_id": s_keys[a:b], "ns": s_ns[a:b],
                 "dirty": np.zeros(b - a, dtype=bool),
                 **{f"leaf_{i}": s_leaves[i][a:b]
                    for i in range(len(s_leaves))}}
        spill_page(spill, pmap, entry, count=False)
        a = b
    # pages were appended in ascending-ns order: the map is sorted
    pmap.sorted = True
