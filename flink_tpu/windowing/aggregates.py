"""Vectorized aggregate functions.

The reference's ``AggregateFunction`` contract (createAccumulator/add/merge/
getResult; reference: flink-core/.../api/common/functions/AggregateFunction.java)
is re-expressed for batched device execution: an aggregate declares its
accumulator as a tuple of *leaves* (flat device arrays, one per accumulator
component), each with a scatter-reduce kind. ``add`` over a whole micro-batch
becomes one donated-buffer XLA scatter per leaf; ``merge`` across window slices
becomes a gather + axis-reduce; ``getResult`` is a jitted elementwise
``finish``.

E.g. AVG = (sum leaf, count leaf), finish = sum/count — identical in spirit to
the reference's AverageAccumulator but with arrays of 2^20 accumulators updated
per kernel launch instead of one object per key.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from flink_tpu.core.records import RecordBatch
from flink_tpu.ops.segment_ops import (
    SCATTER_METHOD,
    identity_for,
    pad_values,
)
from flink_tpu.stateplane import families as _families


from flink_tpu.core.annotations import public

@public
@dataclasses.dataclass(frozen=True)
class AccLeaf:
    """One flat component of an accumulator pytree.

    ``const`` marks a leaf whose per-record input value is a compile-time
    constant (e.g. the ``1`` of COUNT): no host value array is built or
    transferred for it — the scatter kernel broadcasts the constant on
    device. Padded lanes target the reserved identity slot 0, so the
    constant contribution of padding never reaches a live accumulator.
    """

    name: str
    dtype: np.dtype
    reduce: str  # 'sum' | 'max' | 'min'
    const: object = None

    def __post_init__(self):
        object.__setattr__(self, "dtype", np.dtype(self.dtype))
        if self.reduce not in SCATTER_METHOD:
            raise ValueError(f"unsupported reduce {self.reduce!r}")

    @property
    def identity(self):
        return identity_for(self.reduce, self.dtype)


# Compiled steps live in the shared PROGRAM_CACHE via the stateplane
# family builders, keyed by aggregate *layout*, not instance, so two
# pipelines with the same aggregate shape (e.g. a warmup run and a
# measured run, or repeated jobs — or two tenants) share XLA
# executables. The ``_*_jit`` properties below are the engines' stable
# entry points; the program bodies moved verbatim to
# ``flink_tpu/stateplane/families.py`` (bit-identity pinned by
# tests/test_stateplane.py).


@public
class AggregateFunction:
    """Base class. Subclasses define ``leaves``, ``map_input`` and ``finish``."""

    #: accumulator layout
    leaves: Tuple[AccLeaf, ...] = ()
    #: names of the emitted result columns
    output_names: Tuple[str, ...] = ("result",)

    def cache_key(self) -> tuple:
        """Identity of the compiled programs this aggregate needs. Two
        aggregates with equal keys can share jitted executables.

        Includes every hashable-primitive instance attribute so that
        parameterized subclasses (e.g. a scale factor used inside
        ``finish``) do not alias each other's compiled kernels. Subclasses
        whose ``finish`` depends on non-primitive state must override this.
        """
        params = tuple(
            (k, v) for k, v in sorted(vars(self).items())
            if isinstance(v, (str, int, float, bool, bytes, tuple))
        )
        return (type(self).__module__, type(self).__qualname__,
                self.leaves, self.output_names, params)

    # -- host side ----------------------------------------------------------

    def map_input(self, batch: RecordBatch) -> Tuple[np.ndarray, ...]:
        """Extract one value array per leaf from an input batch (host)."""
        raise NotImplementedError

    # -- device side (jax-traceable) ----------------------------------------

    def finish(self, merged: Tuple[jnp.ndarray, ...]) -> Dict[str, jnp.ndarray]:
        """Accumulator leaves -> result columns (traced under jit)."""
        raise NotImplementedError

    # -- compiled steps (shared across operators via this instance) ---------

    def init_accumulators(self, capacity: int) -> Tuple[jnp.ndarray, ...]:
        return tuple(
            jnp.full((capacity,), leaf.identity, dtype=leaf.dtype)
            for leaf in self.leaves
        )

    @property
    def input_leaves(self) -> Tuple[AccLeaf, ...]:
        """Leaves that take a per-record host value array (``const is None``)."""
        return tuple(l for l in self.leaves if l.const is None)

    @property
    def _scatter_jit(self):
        return _families.flat_scatter_combine(self.leaves)

    @property
    def _fire_jit(self):
        """(accs, slot_matrix [w, k]) -> result columns [w] + merged leaves."""
        return _families.flat_segment_fire(self)

    def _fire_project_jit(self, projector):
        """(accs, slot_matrix [wp, k], w scalar) -> projected (row indices
        [n], result cols [n], valid [n]) — the fire merge+finish fused with
        a FireProjector so only n rows cross HBM->host instead of wp. The
        validity mask is derived on device from the scalar row count and
        keys never ship at all (the host resolves indices->keys), keeping
        the fire's host->device traffic to the slot matrix alone (see
        flink_tpu.windowing.fire_projectors)."""
        return _families.flat_segment_fire_projected(self, projector)

    @property
    def _gather_jit(self):
        """(accs, slots) -> per-leaf gathered values — the incremental-
        snapshot read path: only dirty slots leave the device instead of
        the whole [capacity] arrays (HBM->host bandwidth is the cost)."""
        return _families.flat_gather(self.leaves)

    @property
    def _merge_jit(self):
        """(accs, slot_matrix [w, k]) -> merged leaves [w] WITHOUT finish —
        the hybrid-fire read path: device-resident slices merge on device,
        spilled slices merge on host, finish runs on host over the union."""
        return _families.flat_segment_merge(self.leaves)

    @property
    def _put_jit(self):
        """(accs, slots, per-leaf values) -> accs with ``a[slots] = v`` —
        the spill-reload write path: values gathered to host at eviction
        time are placed back verbatim (identity-masked at the reserved
        slot 0 pad target)."""
        return _families.flat_put(self.leaves)

    @property
    def _reset_jit(self):
        return _families.flat_reset(self.leaves)

    # -- retraction (changelog) support -------------------------------------

    @property
    def retractable(self) -> bool:
        """True when every accumulator leaf folds by addition — the
        changelog retract of a row is then the scatter of its negated
        contribution (reference: AggregateFunction.retract / the
        *WithRetractAggFunction family). MAX/MIN leaves are not
        retractable."""
        return all(l.reduce == "sum" for l in self.leaves)

    def map_input_valued(self, batch: RecordBatch) -> Tuple[np.ndarray, ...]:
        """One value array per leaf with const leaves materialized — the
        form needed when every leaf must carry explicit per-row values
        (local pre-aggregation, retraction folds)."""
        vit = iter(self.map_input(batch))
        out = []
        for leaf in self.leaves:
            if leaf.const is not None:
                out.append(np.full(len(batch), leaf.const, dtype=leaf.dtype))
            else:
                out.append(np.asarray(next(vit), dtype=leaf.dtype))
        return tuple(out)

    def map_input_signed(self, batch: RecordBatch,
                         signs: np.ndarray) -> Tuple[np.ndarray, ...]:
        """One SIGNED value array per leaf (const leaves materialized):
        +v for accumulate rows, -v for retraction rows."""
        return tuple(v * signs.astype(v.dtype)
                     for v in self.map_input_valued(batch))

    @property
    def _scatter_valued_jit(self):
        """Scatter where EVERY leaf takes an explicit value array, each
        folded by its own reduce method — the merge of locally pre-
        aggregated partials (two-phase aggregation; reference: the
        local/global split of MiniBatchLocalGroupAggFunction +
        MiniBatchGlobalGroupAggFunction). Pad lanes must carry each leaf's
        identity at the reserved slot 0."""
        return _families.flat_scatter_valued(self.leaves)

    @property
    def _scatter_signed_jit(self):
        """Scatter where EVERY leaf takes a (sign-applied) host value array
        — the retraction fold. Only valid for retractable aggregates
        (pure-add leaves), where padding with 0 at the reserved slot is
        harmless."""
        if not self.retractable:
            raise TypeError(
                f"{type(self).__name__} is not retractable (non-additive "
                "accumulator leaf); an updating input cannot be folded")
        return _families.flat_scatter_signed(self.leaves)

    # -- convenience --------------------------------------------------------

    def pad_input_values(
        self, values: Sequence[np.ndarray], size: int
    ) -> Tuple[np.ndarray, ...]:
        """Pad the value arrays of the non-const leaves (``map_input`` returns
        one array per *input* leaf; const leaves are broadcast on device)."""
        return tuple(
            pad_values(np.asarray(v, dtype=l.dtype), size, l.identity)
            for v, l in zip(values, self.input_leaves)
        )


# ---------------------------------------------------------------------------
# Built-ins
# ---------------------------------------------------------------------------


@public
class SumAggregate(AggregateFunction):
    def __init__(self, field: str, dtype=np.float32, output: str = None):
        self.field = field
        self.leaves = (AccLeaf("sum", dtype, "sum"),)
        self.output_names = (output or f"sum_{field}",)

    def map_input(self, batch):
        return (batch[self.field],)

    def finish(self, merged):
        return {self.output_names[0]: merged[0]}


@public
class CountAggregate(AggregateFunction):
    def __init__(self, output: str = "count"):
        self.leaves = (AccLeaf("count", np.int32, "sum", const=1),)
        self.output_names = (output,)

    def map_input(self, batch):
        return ()

    def finish(self, merged):
        return {self.output_names[0]: merged[0]}


@public
class MaxAggregate(AggregateFunction):
    def __init__(self, field: str, dtype=np.float32, output: str = None):
        self.field = field
        self.leaves = (AccLeaf("max", dtype, "max"),)
        self.output_names = (output or f"max_{field}",)

    def map_input(self, batch):
        return (batch[self.field],)

    def finish(self, merged):
        return {self.output_names[0]: merged[0]}


@public
class MinAggregate(AggregateFunction):
    def __init__(self, field: str, dtype=np.float32, output: str = None):
        self.field = field
        self.leaves = (AccLeaf("min", dtype, "min"),)
        self.output_names = (output or f"min_{field}",)

    def map_input(self, batch):
        return (batch[self.field],)

    def finish(self, merged):
        return {self.output_names[0]: merged[0]}


@public
class AvgAggregate(AggregateFunction):
    def __init__(self, field: str, output: str = None):
        self.field = field
        self.leaves = (
            AccLeaf("sum", np.float32, "sum"),
            AccLeaf("count", np.float32, "sum", const=1.0),
        )
        self.output_names = (output or f"avg_{field}",)

    def map_input(self, batch):
        return (batch[self.field],)

    def finish(self, merged):
        s, c = merged
        return {self.output_names[0]: s / jnp.maximum(c, 1.0)}


@public
class MultiAggregate(AggregateFunction):
    """Compose several aggregates over the same key/window into one state
    table (one scatter pass, multiple result columns)."""

    def __init__(self, aggs: Sequence[AggregateFunction]):
        self.aggs = list(aggs)
        leaves: List[AccLeaf] = []
        outs: List[str] = []
        self._spans = []
        for i, a in enumerate(self.aggs):
            start = len(leaves)
            leaves.extend(
                AccLeaf(f"a{i}_{l.name}", l.dtype, l.reduce, l.const)
                for l in a.leaves
            )
            self._spans.append((start, len(leaves)))
            outs.extend(a.output_names)
        self.leaves = tuple(leaves)
        self.output_names = tuple(outs)

    def cache_key(self):
        return ("multi", tuple(a.cache_key() for a in self.aggs))

    def map_input(self, batch):
        vals: List[np.ndarray] = []
        for a in self.aggs:
            vals.extend(a.map_input(batch))
        return tuple(vals)

    def finish(self, merged):
        out: Dict[str, jnp.ndarray] = {}
        for a, (s, e) in zip(self.aggs, self._spans):
            out.update(a.finish(tuple(merged[s:e])))
        return out
