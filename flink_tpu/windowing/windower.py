"""Slice-shared window engine (host-side control, device-side math).

Combines a ``WindowAssigner`` (timestamps -> slices, windows -> slices) with a
``SlotTable`` (keyed per-slice accumulators on device). This is the semantic
core of the reference's WindowOperator + WindowAggOperator
(reference: streaming/runtime/operators/windowing/WindowOperator.java:293,450,575;
flink-table-runtime/.../window/tvf/common/WindowAggOperator.java:216,232):

- ``process_batch``: vectorized slice assignment, late-record drop, slot
  lookup, one scatter per accumulator leaf.
- ``on_watermark``: fire every pending window with end-1 <= watermark —
  build the [windows*keys, slices_per_window] slot matrix on host, one
  gather+merge+finish kernel on device, then free exhausted slices
  (the reference frees per-window state in clearAllState; here a slice is
  freed after its last participating window fires).

Window lifecycle metadata lives in ``SliceBookkeeper`` (shared with the
mesh-sharded engine). Timers for aligned windows are implicit — window ends
are known at slice creation, replacing the reference's per-(key, window)
timer registrations (reference: InternalTimerServiceImpl.java:314).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.local_agg import is_partial_batch, partial_leaf_values
from flink_tpu.state.slot_table import SlotTable
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.windowing.assigners import WindowAssigner
from flink_tpu.windowing.bookkeeping import SliceBookkeeper

WINDOW_START_FIELD = "window_start"
WINDOW_END_FIELD = "window_end"


def compose_windows(assigner, agg, slice_vals: Dict[int, tuple]
                    ) -> Dict[int, Dict[str, float]]:
    """Slice sharing, host side: one key's ``{slice_end -> per-leaf
    1-element raw accumulator arrays}`` composed into ``{window_end ->
    finished result columns}`` (a sliding window's value = merge of its
    k slices). The ONE copy of the serving-path compose loop —
    ``SliceSharedWindower.query_windows_batch`` and
    ``MeshWindowEngine.query_batch`` read through it, so window/slice
    mapping semantics cannot drift between layouts."""
    from flink_tpu.ops.segment_ops import HOST_COMBINE

    leaves = agg.leaves
    windows = sorted({
        int(w) for se in slice_vals
        for w in assigner.window_ends_for_slice(se)})
    out: Dict[int, Dict[str, float]] = {}
    for w in windows:
        acc = [np.full(1, l.identity, dtype=l.dtype) for l in leaves]
        for se in assigner.slice_ends_for_window(w):
            v = slice_vals.get(int(se))
            if v is None:
                continue
            acc = [HOST_COMBINE[l.reduce](a, x)
                   for a, x, l in zip(acc, v, leaves)]
        finished = agg.finish(tuple(acc))
        out[w] = {name: np.asarray(col).item()
                  for name, col in finished.items()}
    return out


class SliceSharedWindower:
    """Windowed keyed aggregation over one key-group range / device shard."""

    #: on_watermark(async_ok=True) may return PendingFire handles (the
    #: hosting operator/executor owns harvest + watermark holdback)
    supports_async_fires = True

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: AggregateFunction,
        capacity: int = 1 << 16,
        max_parallelism: int = 128,
        allowed_lateness: int = 0,
        spill: dict = None,
        fire_projector=None,
    ) -> None:
        self.assigner = assigner
        self.agg = agg
        self.table = SlotTable(agg, capacity=capacity,
                               max_parallelism=max_parallelism,
                               **(spill or {}))
        self.book = SliceBookkeeper(assigner, allowed_lateness)
        #: optional device-side reduction of each fired window's rows
        #: before host transfer (flink_tpu.windowing.fire_projectors)
        self.fire_projector = fire_projector

    @property
    def late_records_dropped(self) -> int:
        return self.book.late_records_dropped

    # --------------------------------------------------------------- ingest

    def process_batch(self, batch: RecordBatch) -> None:
        n = len(batch)
        if n == 0:
            return
        fused = getattr(self.table, "ingest_indices", None)
        if fused is not None:
            out = fused(batch.key_ids, batch.timestamps,
                        self.assigner.offset, self.assigner.slice_width)
            if out is not None:
                flat, uniq, sinv = out
                self._register_fused(uniq, sinv)
                if is_partial_batch(batch):
                    self.table.scatter_flat(
                        flat, partial_leaf_values(batch, self.agg),
                        valued=True)
                else:
                    self.table.scatter_flat(flat,
                                            self.agg.map_input(batch))
                return
        slice_ends = self.assigner.assign_slice_ends(batch.timestamps)
        live = self.book.live_mask(slice_ends)
        if live is not None:
            slice_ends = slice_ends[live]
            batch = batch.filter(live)
            if len(batch) == 0:
                return
        # one O(n) pass finds the distinct slice ends + inverse; shared by
        # the bookkeeper AND the state table so neither re-sorts the batch
        plan = self.assigner.slice_plan(slice_ends)
        self.book.register_slices(slice_ends, uniq=plan[0])
        accepts_plan = getattr(self.table, "accepts_slice_plan", False)
        kw = {"slice_plan": plan} if accepts_plan else {}
        if is_partial_batch(batch):
            # locally pre-aggregated rows (two-phase agg): fold explicit
            # per-leaf partials instead of re-mapping raw inputs
            self.table.upsert_valued(
                batch.key_ids, slice_ends,
                partial_leaf_values(batch, self.agg), **kw)
        else:
            self.table.upsert(batch.key_ids, slice_ends,
                              self.agg.map_input(batch), **kw)

    def _register_fused(self, uniq: np.ndarray, sinv: np.ndarray) -> None:
        """Bookkeeping for the fused ingest path. Late records are NOT
        filtered out of the scatter (unlike the numpy path): they land in
        slices whose every window is already past retention, so those
        rows are never gathered by a fire and the cleanup heap frees them
        on the next watermark — observable behavior (results + the
        late-drop metric) matches the filtering path without a second
        pass over the batch."""
        book = self.book
        if book.watermark > -(1 << 61):
            last = self.assigner.last_window_ends(uniq)
            late = last - 1 + book.allowed_lateness <= book.watermark
            if late.any():
                book.late_records_dropped += int(
                    np.bincount(sinv, minlength=len(uniq))[late].sum())
        book.register_slices(uniq, uniq=uniq)

    # ----------------------------------------------------------------- fire

    def on_watermark(self, watermark: int,
                     async_ok: bool = False) -> List[RecordBatch]:
        """Fire all windows with end - 1 <= watermark. Returns result
        batches — or, with ``async_ok``, PendingFire handles whose harvest
        yields the batch (the caller owns watermark holdback; see
        flink_tpu.runtime.pending). Slice frees dispatched after the fires
        are device-queue-ordered behind them, so deferring the host read
        never races the reset."""
        out: List[RecordBatch] = []
        while True:
            w_end = self.book.next_window(watermark)
            if w_end is None:
                break
            batch = self._fire_window(w_end, async_ok=async_ok)
            if batch is not None and (not hasattr(batch, "__len__")
                                      or len(batch) > 0):
                out.append(batch)
            self.book.mark_fired(w_end)
        expired = self.book.expired_slices(watermark)
        if expired:
            self.table.free_namespaces(expired)
        return out

    def _wrap_pending(self, pending, window_end: int):
        """Compose the table-level PendingFire (keys, result cols) with the
        window-metadata column assembly."""
        if pending is None:
            return None
        inner = pending.build
        w_start = self.assigner.window_start(window_end)

        def build(host):
            keys, results = inner(host)
            m = len(keys)
            if m == 0:
                return None
            cols = {
                KEY_ID_FIELD: keys,
                WINDOW_START_FIELD: np.full(m, w_start, dtype=np.int64),
                WINDOW_END_FIELD: np.full(m, window_end, dtype=np.int64),
                TIMESTAMP_FIELD: np.full(m, window_end - 1, dtype=np.int64),
            }
            cols.update(results)
            return RecordBatch(cols)

        pending.build = build
        return pending

    def _fire_window(self, window_end: int,
                     async_ok: bool = False) -> Optional[RecordBatch]:
        slice_ends = self.assigner.slice_ends_for_window(window_end)
        if any(int(se) in self.table.spill for se in slice_ends):
            # hybrid fire: resident slices merge on device, spilled slices
            # merge on host — no residency requirement, so the device
            # budget is independent of the window's slice count
            keys, results = self.table.fire_hybrid(
                [int(se) for se in slice_ends])
            if len(keys) == 0:
                return None
            if self.fire_projector is not None:
                keys, results = self.fire_projector.project_host(
                    keys, results)
            m = len(keys)
            cols = {
                KEY_ID_FIELD: keys,
                WINDOW_START_FIELD: np.full(
                    m, self.assigner.window_start(window_end),
                    dtype=np.int64),
                WINDOW_END_FIELD: np.full(m, window_end, dtype=np.int64),
                TIMESTAMP_FIELD: np.full(m, window_end - 1, dtype=np.int64),
            }
            cols.update(results)
            return RecordBatch(cols)
        k = len(slice_ends)
        if k == 1:
            # single-slice (tumbling) fast path: no cross-slice unique
            slots = self.table.slots_for_namespace(slice_ends[0])
            if len(slots) == 0:
                return None
            keys = self.table.keys_of_slots(slots)
            matrix = slots[:, None].astype(np.int32)
        else:
            keys, matrix = self.table.build_slice_matrix(
                [int(se) for se in slice_ends])
            if keys is None:
                return None
        if self.fire_projector is not None:
            if async_ok:
                return self._wrap_pending(
                    self.table.fire_projected_async(
                        matrix, keys, self.fire_projector), window_end)
            keys, results = self.table.fire_projected(
                matrix, keys, self.fire_projector)
        else:
            if async_ok:
                return self._wrap_pending(
                    self.table.fire_async(matrix, keys), window_end)
            results = self.table.fire(matrix)
        m = len(keys)
        cols = {
            KEY_ID_FIELD: keys,
            WINDOW_START_FIELD: np.full(
                m, self.assigner.window_start(window_end), dtype=np.int64),
            WINDOW_END_FIELD: np.full(m, window_end, dtype=np.int64),
            TIMESTAMP_FIELD: np.full(m, window_end - 1, dtype=np.int64),
        }
        cols.update(results)
        return RecordBatch(cols)

    # ---------------------------------------------------------- point query

    def query_windows(self, key_id: int) -> Dict[int, Dict[str, float]]:
        """Queryable-state point lookup: {window_end -> result columns} —
        same contract as MeshWindowEngine.query_windows."""
        return self.table.query_windows(key_id, self.assigner)

    def query_windows_batch(self, key_ids) -> List[Dict[int, Dict[str, float]]]:
        """Batched point lookup: one result dict per requested key, the
        whole batch served by ONE gather kernel + ONE device read
        (``SlotTable.query_batch_pairs`` over keys x live slices) —
        the serving plane's per-request-batch cost model."""
        key_ids = np.asarray(key_ids, dtype=np.int64)
        n = len(key_ids)
        if n == 0:
            return []
        if not hasattr(self.table, "query_batch_pairs"):
            # pane/ring layout: no pair-gather primitive — per key
            return [self.query_windows(int(k)) for k in key_ids]
        live_ns = np.asarray([int(x) for x in self.table.namespaces],
                             dtype=np.int64)
        if len(live_ns) == 0:
            return [{} for _ in range(n)]
        pair_keys = np.repeat(key_ids, len(live_ns))
        pair_ns = np.tile(live_ns, n)
        found, leaves = self.table.query_batch_pairs(pair_keys, pair_ns)
        agg = self.agg
        results: List[Dict[int, Dict[str, float]]] = []
        k = len(live_ns)
        for r in range(n):
            base = r * k
            sv = {int(pair_ns[base + j]):
                  tuple(l[base + j:base + j + 1] for l in leaves)
                  for j in range(k) if found[base + j]}
            results.append(compose_windows(self.assigner, agg, sv)
                           if sv else {})
        return results

    # ------------------------------------------------------------- snapshot

    def snapshot(self, mode: str = "full") -> Dict[str, object]:
        """mode: "full" (new incremental base), "delta" (dirty rows only),
        "savepoint" (full, but preserves dirty tracking — a side artifact
        must not change what the next delta checkpoint contains)."""
        if mode == "delta":
            table = self.table.snapshot_delta()
        else:
            table = self.table.snapshot(reset_dirty=(mode != "savepoint"))
        return {
            "table": table,
            **self.book.snapshot(),
        }

    def restore(self, snap: Dict[str, object], key_group_filter=None) -> None:
        self.table.restore(snap["table"], key_group_filter=key_group_filter)
        self.book.restore(snap)


class PaneWindower(SliceSharedWindower):
    """SliceSharedWindower over the pane/ring layout (state/pane_table.py):
    same external contract, but fires are pure device reductions over ring
    rows — no host-built slot matrix, no per-fire host->device transfer —
    and freeing an expired slice is one index-free row reset.

    With ``preagg`` (latency.fire-deadline tier, default on), the layout
    additionally maintains a RUNNING PARTIAL ring row per pending window,
    combined at absorb: each record scatters into its pane AND into every
    pending window containing that pane, in the same single flat-index
    dispatch. A watermark fire then gathers exactly ONE ring row — the
    pane that closes — instead of merging the window's k slice rows (the
    full-window harvest, which remains the fallback for windows without a
    maintained partial and for ``preagg=False``). Partials are DERIVED
    state: snapshots carry only the panes, restore/compaction refold the
    pending windows' rows from them, and a late re-registration under
    allowed lateness refolds too. Float sums fold in record order rather
    than per-slice order, so f32 results can differ from the full harvest
    in the last ulp (count/min/max and integer-valued sums are exact).

    Opt-in via state.window-layout=panes for aligned (non-merging)
    assigners without a spill tier at parallelism 1 ('auto' resolves to
    the slot layout until hardware measurements land); the slot layout
    stays the engine for sessions, spill, and the mesh.
    """

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: AggregateFunction,
        capacity: int = 1 << 16,
        max_parallelism: int = 128,
        allowed_lateness: int = 0,
        fire_projector=None,
        memory=None,
        preagg: bool = True,
    ) -> None:
        from flink_tpu.state.pane_table import PaneTable

        self.assigner = assigner
        self.agg = agg
        # pre-aggregation only pays when windows SHARE panes: for
        # single-slice (tumbling) windows the partial would be an exact
        # duplicate of the pane — double the scatter volume and ring
        # rows for a fire that already gathers one row (k == 1)
        self._preagg = bool(preagg) and int(
            getattr(assigner, "slices_per_window", 1)) > 1
        self.table = PaneTable(agg, capacity=capacity,
                               max_parallelism=max_parallelism,
                               fire_projector=fire_projector,
                               memory=memory,
                               slices_for_window=(
                                   assigner.slice_ends_for_window
                                   if self._preagg else None))
        self.book = SliceBookkeeper(assigner, allowed_lateness)
        self.fire_projector = fire_projector

    # --------------------------------------------------------------- ingest

    def process_batch(self, batch: RecordBatch) -> None:
        if not self._preagg:
            return super().process_batch(batch)
        n = len(batch)
        if n == 0:
            return
        table = self.table
        flat = uniq = sinv = None
        fused = getattr(table, "ingest_indices", None)
        if fused is not None:
            out = fused(batch.key_ids, batch.timestamps,
                        self.assigner.offset, self.assigner.slice_width)
            if out is not None:
                flat, uniq, sinv = out
                self._register_fused(uniq, sinv)
        if flat is None:
            slice_ends = self.assigner.assign_slice_ends(batch.timestamps)
            live = self.book.live_mask(slice_ends)
            if live is not None:
                slice_ends = slice_ends[live]
                batch = batch.filter(live)
                if len(batch) == 0:
                    return
            plan = self.assigner.slice_plan(slice_ends)
            self.book.register_slices(slice_ends, uniq=plan[0])
            uniq, sinv = plan
            flat = table._flat_indices(batch.key_ids, slice_ends, plan)
        # combine-on-absorb: fold each record into its pending windows'
        # partial rows in the SAME scatter. Only windows that already
        # have a row get direct folds — everything else (new windows,
        # late re-registrations, restored/compacted state) is refolded
        # from the authoritative panes right after.
        pending = self.book.pending_windows()
        wins = [[w for w in self.assigner.window_ends_for_slice(int(se))
                 if w in pending and table.has_window_partial(w)]
                for se in uniq.tolist()]
        win = table.window_flat(flat % np.int32(table.capacity), sinv,
                                wins)
        if is_partial_batch(batch):
            table.scatter_combined(
                flat, win, partial_leaf_values(batch, self.agg),
                valued=True)
        else:
            table.scatter_combined(flat, win, self.agg.map_input(batch))
        table.rebuild_window_partials(pending)

    # ----------------------------------------------------------------- fire

    def _fire_window(self, window_end: int,
                     async_ok: bool = False) -> Optional[RecordBatch]:
        if self._preagg and self.table.has_window_partial(window_end):
            # delta harvest: ONE ring row — the pane that closes
            if async_ok:
                return self._wrap_pending(
                    self.table.fire_partial_async(window_end), window_end)
            keys, results = self.table.fire_partial(window_end)
            return self._assemble(window_end, keys, results)
        # full-window harvest (fallback: preagg off, or no partial row)
        slice_ends = [int(se)
                      for se in self.assigner.slice_ends_for_window(
                          window_end)]
        if async_ok:
            return self._wrap_pending(
                self.table.fire_window_async(slice_ends), window_end)
        keys, results = self.table.fire_window(slice_ends)
        return self._assemble(window_end, keys, results)

    def _assemble(self, window_end: int, keys,
                  results) -> Optional[RecordBatch]:
        if len(keys) == 0:
            return None
        m = len(keys)
        cols = {
            KEY_ID_FIELD: keys,
            WINDOW_START_FIELD: np.full(
                m, self.assigner.window_start(window_end), dtype=np.int64),
            WINDOW_END_FIELD: np.full(m, window_end, dtype=np.int64),
            TIMESTAMP_FIELD: np.full(m, window_end - 1, dtype=np.int64),
        }
        cols.update(results)
        return RecordBatch(cols)

    # ------------------------------------------------------------- snapshot

    def restore(self, snap, key_group_filter=None) -> None:
        if self._preagg:
            # partial rows are derived: drop any stale ones, land the
            # panes, then refold the pending windows' partials
            self.table.clear_window_rows()
        super().restore(snap, key_group_filter=key_group_filter)
        if self._preagg:
            self.table.rebuild_window_partials(
                self.book.pending_windows())
