"""Slice-shared window engine (host-side control, device-side math).

Combines a ``WindowAssigner`` (timestamps -> slices, windows -> slices) with a
``SlotTable`` (keyed per-slice accumulators on device). This is the semantic
core of the reference's WindowOperator + WindowAggOperator
(reference: streaming/runtime/operators/windowing/WindowOperator.java:293,450,575;
flink-table-runtime/.../window/tvf/common/WindowAggOperator.java:216,232):

- ``process_batch``: vectorized slice assignment, late-record drop, slot
  lookup, one scatter per accumulator leaf.
- ``on_watermark``: fire every pending window with end-1 <= watermark —
  build the [windows*keys, slices_per_window] slot matrix on host, one
  gather+merge+finish kernel on device, then free exhausted slices
  (the reference frees per-window state in clearAllState; here a slice is
  freed after its last participating window fires).

Timers for aligned windows are implicit (window ends are known at slice
creation), replacing the reference's per-(key, window) timer registrations
(reference: InternalTimerServiceImpl.java:314 advanceWatermark).
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Set

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.state.slot_table import SlotTable
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.windowing.assigners import WindowAssigner

WINDOW_START_FIELD = "window_start"
WINDOW_END_FIELD = "window_end"


class SliceSharedWindower:
    """Windowed keyed aggregation over one key-group range / device shard."""

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: AggregateFunction,
        capacity: int = 1 << 16,
        max_parallelism: int = 128,
        allowed_lateness: int = 0,
    ) -> None:
        self.assigner = assigner
        self.agg = agg
        self.table = SlotTable(agg, capacity=capacity,
                               max_parallelism=max_parallelism)
        self.allowed_lateness = allowed_lateness
        # pending window ends (min-heap + dedup set)
        self._pending: List[int] = []
        self._pending_set: Set[int] = set()
        # slice end -> last window end (freed after that window fires)
        self._slice_last_window: Dict[int, int] = {}
        # window end -> slice ends to free after firing it
        self._free_after: Dict[int, List[int]] = {}
        self._max_fired_end: int = -(1 << 62)
        self.late_records_dropped = 0

    # --------------------------------------------------------------- ingest

    def process_batch(self, batch: RecordBatch) -> None:
        n = len(batch)
        if n == 0:
            return
        ts = batch.timestamps
        key_ids = batch.key_ids
        slice_ends = self.assigner.assign_slice_ends(ts)

        # Late-record handling: a record is late iff every window of its slice
        # already fired (reference: WindowOperator.java:293 isWindowLate /
        # sideOutput path; default lateness 0).
        horizon = self._max_fired_end - self.allowed_lateness
        if self._max_fired_end > -(1 << 61):
            last_ends = slice_ends + self.assigner.size - self.assigner.slice_width
            live = last_ends > horizon
            dropped = n - int(live.sum())
            if dropped:
                self.late_records_dropped += dropped
                key_ids = key_ids[live]
                slice_ends = slice_ends[live]
                batch = batch.filter(live)
                if len(batch) == 0:
                    return

        # register new slices' windows
        for se in np.unique(slice_ends).tolist():
            if se not in self._slice_last_window:
                ends = self.assigner.window_ends_for_slice(se)
                last = ends[-1]
                self._slice_last_window[se] = last
                self._free_after.setdefault(last, []).append(se)
                for w in ends:
                    if w > self._max_fired_end and w not in self._pending_set:
                        self._pending_set.add(w)
                        heapq.heappush(self._pending, w)

        slots = self.table.lookup_or_insert(key_ids, slice_ends)
        values = self.agg.map_input(batch)
        self.table.scatter(slots, values)

    # ----------------------------------------------------------------- fire

    def on_watermark(self, watermark: int) -> List[RecordBatch]:
        """Fire all windows with end - 1 <= watermark. Returns result batches."""
        out: List[RecordBatch] = []
        while self._pending and self._pending[0] - 1 <= watermark:
            w_end = heapq.heappop(self._pending)
            self._pending_set.discard(w_end)
            batch = self._fire_window(w_end)
            if batch is not None and len(batch) > 0:
                out.append(batch)
            self._max_fired_end = max(self._max_fired_end, w_end)
            self._release_after(w_end)
        return out

    def _fire_window(self, window_end: int) -> Optional[RecordBatch]:
        slice_ends = self.assigner.slice_ends_for_window(window_end)
        k = len(slice_ends)
        per_slice = [(i, self.table.slots_for_namespace(se))
                     for i, se in enumerate(slice_ends)]
        per_slice = [(i, s) for i, s in per_slice if len(s) > 0]
        if not per_slice:
            return None
        if len(per_slice) == 1 and k == 1:
            slots = per_slice[0][1]
            keys = self.table.keys_of_slots(slots)
            matrix = slots[:, None].astype(np.int32)
        else:
            all_slots = np.concatenate([s for _, s in per_slice])
            all_slice_idx = np.concatenate(
                [np.full(len(s), i, dtype=np.int32) for i, s in per_slice])
            all_keys = self.table.keys_of_slots(all_slots)
            keys, inv = np.unique(all_keys, return_inverse=True)
            matrix = np.zeros((len(keys), k), dtype=np.int32)
            matrix[inv, all_slice_idx] = all_slots
        results = self.table.fire(matrix)
        m = len(keys)
        cols = {
            KEY_ID_FIELD: keys,
            WINDOW_START_FIELD: np.full(
                m, self.assigner.window_start(window_end), dtype=np.int64),
            WINDOW_END_FIELD: np.full(m, window_end, dtype=np.int64),
            TIMESTAMP_FIELD: np.full(m, window_end - 1, dtype=np.int64),
        }
        cols.update(results)
        return RecordBatch(cols)

    def _release_after(self, window_end: int) -> None:
        ends = self._free_after.pop(window_end, None)
        if not ends:
            return
        for se in ends:
            self._slice_last_window.pop(se, None)
        self.table.free_namespaces(ends)

    # ------------------------------------------------------------- snapshot

    def snapshot(self) -> Dict[str, object]:
        return {
            "table": self.table.snapshot(),
            "pending": sorted(self._pending),
            "slice_last_window": dict(self._slice_last_window),
            "max_fired_end": self._max_fired_end,
        }

    def restore(self, snap: Dict[str, object], key_group_filter=None) -> None:
        self.table.restore(snap["table"], key_group_filter=key_group_filter)
        self._pending = list(snap["pending"])
        heapq.heapify(self._pending)
        self._pending_set = set(self._pending)
        self._slice_last_window = dict(snap["slice_last_window"])
        self._free_after = {}
        for se, last in self._slice_last_window.items():
            self._free_after.setdefault(last, []).append(se)
        self._max_fired_end = snap["max_fired_end"]
