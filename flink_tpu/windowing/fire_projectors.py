"""Fire-time device-side projection of window results.

The reference executes Top-N over a window's output as a separate rank
operator consuming the full fired stream (reference:
flink-table-runtime/.../operators/rank/AppendOnlyTopNFunction.java). On TPU
the expensive part of a fire is not the merge kernel but moving the [num_keys]
result rows from HBM to the host: Nexmark Q5 fires ~100k rows per HOP window
only for the next operator to keep one winner.

A ``FireProjector`` fuses that reduction INTO the fire kernel: the window's
result columns are reduced on device (``jax.lax.top_k``) and only the
projected rows are transferred. Because a fire always covers every key of the
window, the device-side reduction is exact — it is the same fusion XLA cannot
do on its own because the consumer lives in a different operator.

The projector also has a NumPy form (``project_host``) for the fire paths
that merge on host (spilled slices, cross-shard mesh merges).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax


class FireProjector:
    """Reduces the [w] rows of one fired window before host transfer.

    ``num_out`` is static (XLA shapes); ``project`` runs under jit inside
    the fire kernel; ``project_host`` is the NumPy equivalent.
    """

    #: static number of output rows per fired window
    num_out: int = 1

    def cache_key(self) -> tuple:
        raise NotImplementedError

    def project(self, cols: Dict[str, jnp.ndarray], valid: jnp.ndarray
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], jnp.ndarray]:
        """(result cols[wp], valid[wp]) -> (row indices[n], cols[n],
        valid[n]) — jax-traced. Returns INDICES into the fired rows, not
        keys: the host resolves keys locally, so no key array ever crosses
        host->device (transfers are the scarce resource on a tunneled
        backend)."""
        raise NotImplementedError

    def project_host(self, keys: np.ndarray, cols: Dict[str, np.ndarray]
                     ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        raise NotImplementedError


class TopKFireProjector(FireProjector):
    """Keep the k rows with the largest (or smallest) ``order_col``.

    Exact for any consumer that keeps at most k rows ordered by that column
    (rank/Top-N, per-window arg-max). Ties beyond the k-th row are truncated
    — consumers that must surface ALL ties of the max should use a k of a
    few ties' headroom (the fused consumer filters to the true extremum).
    """

    def __init__(self, order_col: str, k: int = 16, descending: bool = True):
        self.order_col = order_col
        self.k = int(k)
        self.descending = descending
        self.num_out = self.k

    def cache_key(self) -> tuple:
        return (type(self).__module__, type(self).__qualname__,
                self.order_col, self.k, self.descending)

    def project(self, cols, valid):
        score = cols[self.order_col]
        if jnp.issubdtype(score.dtype, jnp.integer) and self.descending:
            # keep integer ordering exact in the column's own dtype (a
            # float32 cast collapses counts above 2^24). Ascending integer
            # order falls through to the float path: negating iinfo.min
            # would wrap, and x64 may be disabled (no wider int to cast to).
            floor = jnp.asarray(jnp.iinfo(score.dtype).min, score.dtype)
            score = jnp.where(valid, score, floor)
        else:
            score = score.astype(jnp.float32)
            if not self.descending:
                score = -score
            score = jnp.where(valid, score, -jnp.inf)
        k = min(self.k, int(score.shape[0]))
        _, idx = lax.top_k(score, k)
        out_valid = jnp.take(valid, idx)
        out_cols = {name: jnp.take(c, idx) for name, c in cols.items()}
        return idx, out_cols, out_valid

    def project_host(self, keys, cols):
        score = np.asarray(cols[self.order_col], dtype=np.float64)
        k = min(self.k, len(score))
        if self.descending:
            idx = np.argpartition(-score, k - 1)[:k] if k < len(score) \
                else np.arange(len(score))
            idx = idx[np.argsort(-score[idx], kind="stable")]
        else:
            idx = np.argpartition(score, k - 1)[:k] if k < len(score) \
                else np.arange(len(score))
            idx = idx[np.argsort(score[idx], kind="stable")]
        return keys[idx], {name: np.asarray(c)[idx]
                           for name, c in cols.items()}
