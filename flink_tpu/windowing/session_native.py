"""Native session-metadata plane: the ctypes wrapper over
``native/sessions.cpp``.

One C sweep per batch replaces the numpy hot loop of
:class:`flink_tpu.windowing.session_meta.SessionIntervalSet`:

- **absorb**: stable (key, ts) sort + sessionize + interval-index
  probe/extend/create + sid allocation + fire-candidate pushes run in
  ONE native pass over the batch columns (``sx_absorb``). The slow path
  (keys holding >= 2 live sessions, disjoint second sessions) stays in
  Python with exact reference semantics — the sweep flags those
  sessions and the base class's ``_merge_session`` handles them against
  the same store through the ctypes facade.
- **slot folding**: each metadata row carries the session's device-plane
  slot (``dslot``). Engines VERIFY a folded slot against the state
  table's own metadata views before trusting it (see
  ``state.slot_table.verify_slot_hints``), so singleton sessions — the
  overwhelming majority at high key cardinality — never touch the
  state-plane hash probe, and a stale fold costs a fallback probe,
  never a wrong row.
- **pop**: fire candidates live as native columnar chunks with cached
  ``[lo, hi]`` end bounds; ``sx_pop`` cuts, validates and removes fired
  singles in C and returns (key, start, end, sid, slot) columns ready
  for flat staging and ``free_slots(keys=, nss=)``.

The pure-Python plane remains the bit-identical fallback
(``FLINK_TPU_NO_NATIVE=1`` / ``FLINK_TPU_NATIVE=0`` / compiler absent);
:func:`flink_tpu.windowing.session_meta.make_session_meta` selects per
engine, the way ``make_slot_index`` already does for the state plane.
"""

from __future__ import annotations

import ctypes as _ct
import time
from typing import List, Optional, Tuple

import numpy as np

from flink_tpu.windowing.session_meta import (
    AbsorbResult,
    NativePlaneError,
    PopResult,
    SessionIntervalSet,
)

#: hoisted ctypes pointer types (one construction per process — the
#: sweep runs once per batch per engine)
_I64P = _ct.POINTER(_ct.c_int64)
_I32P = _ct.POINTER(_ct.c_int32)
_U8P = _ct.POINTER(_ct.c_uint8)

_FLAG_FRESH = 0
_FLAG_EXTENDED = 1
_FLAG_SLOW = 2
_FLAG_STALE = 3


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(_I64P)


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(_I32P)


class _NativeSessionStore:
    """``make_slot_index``-shaped facade over the C session table.

    Sessions are keyed by key only (one metadata row per key in the
    singles store), so the ``namespaces`` argument the base-class slow
    paths pass is accepted and ignored. Interval columns (start / end /
    sid / folded dslot) are exposed as zero-copy NumPy views, re-wrapped
    after any call that can grow the table.
    """

    def __init__(self, lib, capacity: int = 1 << 16,
                 max_capacity: int = 1 << 28, on_grow=None) -> None:
        self._lib = lib
        self.on_grow = on_grow
        self._h = lib.sx_create(int(capacity), int(max_capacity))
        self._wrap_views()

    def _wrap_views(self) -> None:
        cap = int(self._lib.sx_capacity(self._h))
        self.capacity = cap
        h = self._h
        self.slot_key = np.ctypeslib.as_array(self._lib.sx_keys(h),
                                              shape=(cap,))
        self.start = np.ctypeslib.as_array(self._lib.sx_starts(h),
                                           shape=(cap,))
        self.end = np.ctypeslib.as_array(self._lib.sx_ends(h),
                                         shape=(cap,))
        self.sid = np.ctypeslib.as_array(self._lib.sx_sids(h),
                                         shape=(cap,))
        self.dslot = np.ctypeslib.as_array(self._lib.sx_dslots(h),
                                           shape=(cap,))
        self.slot_used = np.ctypeslib.as_array(
            self._lib.sx_used_mask(h), shape=(cap,)).view(bool)

    def _maybe_rewrap(self) -> None:
        if int(self._lib.sx_capacity(self._h)) != self.capacity:
            self._wrap_views()
            if self.on_grow is not None:
                self.on_grow()

    def destroy(self) -> None:
        if getattr(self, "_h", None):
            self._lib.sx_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - finalizer
        try:
            self.destroy()
        except Exception:
            pass

    @property
    def num_used(self) -> int:
        return int(self._lib.sx_used(self._h))

    def used_slots(self) -> np.ndarray:
        return np.nonzero(self.slot_used)[0]

    def lookup(self, key_ids: np.ndarray, namespaces=None) -> np.ndarray:
        keys = np.ascontiguousarray(key_ids, dtype=np.int64)
        out = np.empty(len(keys), dtype=np.int32)
        self._lib.sx_lookup(self._h, len(keys), _i64p(keys), _i32p(out))
        return out

    def lookup_or_insert(self, key_ids: np.ndarray,
                         namespaces=None) -> np.ndarray:
        keys = np.ascontiguousarray(key_ids, dtype=np.int64)
        out = np.empty(len(keys), dtype=np.int32)
        rc = self._lib.sx_insert(self._h, len(keys), _i64p(keys),
                                 _i32p(out))
        if rc < 0:
            raise NativePlaneError(
                "native session store full (capacity="
                f"{self.capacity}) — raise its max capacity")
        if rc > 0:
            self._wrap_views()
            if self.on_grow is not None:
                self.on_grow()
        return out

    def free_slots(self, slots: np.ndarray, keys=None, nss=None) -> None:
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        if len(slots):
            self._lib.sx_erase_rows(self._h, len(slots), _i32p(slots))


def native_absorb(store: _NativeSessionStore, keys: np.ndarray,
                  ts: np.ndarray, gap: int, lateness: int,
                  max_fired_wm: int, next_sid: int):
    """The raw fused-sweep call: one ``sx_absorb`` per (engine, batch).

    Returns ``(m, n_fast, order, rec_to_sess, sess_key, sess_start,
    sess_end, sess_sid, sess_slot, sess_row, sess_flag)`` with the
    per-session arrays trimmed to the ``m`` batch-local sessions.
    ``sess_row`` is each fast-path session's metadata row — the fold
    writeback is a direct array scatter instead of a hash pass. Rooted
    in flint's HOT_MODULE_ROOTS — this is a per-batch hot entry point.
    """
    n = len(keys)
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    ts = np.ascontiguousarray(ts, dtype=np.int64)
    order = np.empty(n, dtype=np.int64)
    rec_to_sess = np.empty(n, dtype=np.int64)
    sess_key = np.empty(n, dtype=np.int64)
    sess_start = np.empty(n, dtype=np.int64)
    sess_end = np.empty(n, dtype=np.int64)
    sess_sid = np.empty(n, dtype=np.int64)
    sess_slot = np.empty(n, dtype=np.int32)
    sess_row = np.empty(n, dtype=np.int32)
    sess_flag = np.empty(n, dtype=np.uint8)
    n_fast = _ct.c_int64()
    m = store._lib.sx_absorb(
        store._h, n, _i64p(keys), _i64p(ts),
        int(gap), int(lateness), int(max_fired_wm), int(next_sid),
        _i64p(order), _i64p(rec_to_sess),
        _i64p(sess_key), _i64p(sess_start), _i64p(sess_end),
        _i64p(sess_sid), _i32p(sess_slot), _i32p(sess_row),
        sess_flag.ctypes.data_as(_U8P), _ct.byref(n_fast))
    if m < 0:
        raise NativePlaneError(
            "native session store full during absorb — raise its max "
            "capacity")
    store._maybe_rewrap()
    return (int(m), int(n_fast.value), order, rec_to_sess,
            sess_key[:m], sess_start[:m], sess_end[:m], sess_sid[:m],
            sess_slot[:m], sess_row[:m], sess_flag[:m])


def native_pop(store: _NativeSessionStore, watermark: int):
    """The raw chunk-pop call: cut + validate + remove fired singles in
    C. Returns ``((keys, starts, ends, sids, slots), (rest_keys,
    rest_sids, rest_ends))`` — rest rows belong to multi-interval keys
    and are walked by the Python caller. Rooted in HOT_MODULE_ROOTS."""
    n_rest = _ct.c_int64()
    n = int(store._lib.sx_pop(store._h, int(watermark),
                              _ct.byref(n_rest)))
    keys = np.empty(n, dtype=np.int64)
    starts = np.empty(n, dtype=np.int64)
    ends = np.empty(n, dtype=np.int64)
    sids = np.empty(n, dtype=np.int64)
    slots = np.empty(n, dtype=np.int32)
    if n:
        store._lib.sx_pop_fetch(store._h, _i64p(keys), _i64p(starts),
                                _i64p(ends), _i64p(sids), _i32p(slots))
    nr = int(n_rest.value)
    rk = np.empty(nr, dtype=np.int64)
    rs = np.empty(nr, dtype=np.int64)
    re = np.empty(nr, dtype=np.int64)
    if nr:
        store._lib.sx_pop_fetch_rest(store._h, _i64p(rk), _i64p(rs),
                                     _i64p(re))
    return (keys, starts, ends, sids, slots), (rk, rs, re)


class NativeSessionIntervalSet(SessionIntervalSet):
    """SessionIntervalSet with the hot paths replaced by the C sweep.

    Bit-identity discipline: every classification, push order and
    validation rule in ``sx_absorb`` / ``sx_pop`` mirrors the base
    class line by line (same stable sort, same fast/slow split, same
    chunk cut); the slow paths ARE the base class's, run against the C
    store through the ``make_slot_index``-shaped facade. Fires and
    snapshots are pinned bit-identical across planes by
    tests/test_native_sessions.py.
    """

    def __init__(self, gap: int, allowed_lateness: int = 0):
        from flink_tpu.native import load_sessions

        self._lib = load_sessions()
        assert self._lib is not None, \
            "NativeSessionIntervalSet requires the native sessions library"
        self._store: Optional[_NativeSessionStore] = None
        super().__init__(gap, allowed_lateness)

    # ------------------------------------------------------------ store

    def _reset_store(self) -> None:
        if self._store is not None:
            self._store.destroy()
        self._store = _NativeSessionStore(self._lib,
                                          on_grow=self._rebind_views)
        self._idx = self._store
        self._rebind_views()
        self._multi.clear()

    def _rebind_views(self) -> None:
        st = self._store
        self._s_start = st.start
        self._s_end = st.end
        self._s_sid = st.sid

    def _on_grow(self, old: int, new: int) -> None:  # pragma: no cover
        # growth re-binds through the store's on_grow callback instead
        self._rebind_views()

    def _intervals_of(self, key: int):
        # scalar-ctypes fast path: the slow path probes one key at a
        # time, and the base class's 1-element array round trip cost
        # more in pointer marshalling than the probe itself
        ivs = self._multi.get(key)
        if ivs is not None:
            return ivs
        row = int(self._lib.sx_lookup1(self._store._h, int(key)))
        if row < 0:
            return None
        return [(int(self._s_start[row]), int(self._s_end[row]),
                 int(self._s_sid[row]))]

    def _store_intervals(self, key: int,
                         ivs: List[Tuple[int, int, int]]) -> None:
        # scalar write-back + multi-membership mirroring into the
        # native set (the sweep classifies against it)
        lib, h = self._lib, self._store._h
        key = int(key)
        row = int(lib.sx_lookup1(h, key))
        if len(ivs) == 1:
            self._multi.pop(key, None)
            lib.sx_multi_remove(h, key)
            if row < 0:
                row = int(lib.sx_insert1(h, key))
                if row < 0:
                    raise NativePlaneError(
                        "native session store full — raise its max "
                        "capacity")
                self._store._maybe_rewrap()
            s, e, sid = ivs[0]
            self._s_start[row] = s
            self._s_end[row] = e
            self._s_sid[row] = sid
        else:
            if row >= 0:
                lib.sx_erase1(h, row)
            ivs.sort()
            self._multi[key] = ivs
            lib.sx_multi_add(h, key)

    def note_slots(self, keys: np.ndarray, sids: np.ndarray,
                   slots: np.ndarray, rows=None) -> None:
        if not len(keys):
            return
        if rows is not None:
            # fold by direct row access: the rows came out of THIS
            # batch's sweep and row ids are stable across the slow loop
            # (grow reallocs in place, erases touch other keys). The
            # sid guard in sx_fold_rows drops any row a slow-path merge
            # re-purposed.
            rows = np.ascontiguousarray(rows, dtype=np.int32)
            sids = np.ascontiguousarray(sids, dtype=np.int64)
            slots = np.ascontiguousarray(slots, dtype=np.int32)
            self._lib.sx_fold_rows(self._store._h, len(rows),
                                   _i32p(rows), _i64p(sids),
                                   _i32p(slots))
            return
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        sids = np.ascontiguousarray(sids, dtype=np.int64)
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        self._lib.sx_fold(self._store._h, len(keys), _i64p(keys),
                          _i64p(sids), _i32p(slots))

    # ----------------------------------------------------------- absorb

    def absorb_batch_ex(self, keys: np.ndarray, ts: np.ndarray,
                        want_fresh: bool = True) -> AbsorbResult:
        # want_fresh is accepted for interface parity and ignored: the
        # sweep's flag column makes the fresh mask a free compare
        t0 = time.perf_counter()
        (m, n_fast, order, rec_to_sess, sess_key, sess_start, sess_end,
         sess_sid, sess_slot, sess_row, sess_flag) = native_absorb(
            self._store, keys, ts, self.gap, self.allowed_lateness,
            self.max_fired_watermark, self._next_sid)
        self._next_sid += n_fast
        self.native_sweep_s += time.perf_counter() - t0
        # slow path: multi-flavored sessions + disjoint seconds, exact
        # reference semantics in the base class, ascending (key, ts)
        slow = np.nonzero(sess_flag == _FLAG_SLOW)[0]
        if len(slow):
            self._groups, self._cur = [], None
            self._cur_dst, self._cur_src = set(), set()
            for j in slow:
                sess_sid[j] = self._merge_session(
                    int(sess_key[j]), int(sess_start[j]),
                    int(sess_end[j]))
            groups = self._groups
            if self._cur is not None and len(self._cur):
                groups.append(self._cur)
            self._groups, self._cur = [], None
        else:
            groups = []
        return AbsorbResult(sess_key, sess_sid, rec_to_sess, order,
                            groups, sess_flag == _FLAG_FRESH, sess_slot,
                            sess_row)

    def absorb_batch(self, keys: np.ndarray, ts: np.ndarray):
        r = self.absorb_batch_ex(keys, ts)
        return r.sess_key, r.sess_sid, r.rec_to_sess, r.order, r.groups

    # ------------------------------------------------------------- fire

    def _push_fires(self, ends: np.ndarray, keys: np.ndarray,
                    sids: np.ndarray) -> None:
        n = len(ends)
        if not n:
            return
        e = np.ascontiguousarray(ends, dtype=np.int64)
        k = np.ascontiguousarray(keys, dtype=np.int64)
        s = np.ascontiguousarray(sids, dtype=np.int64)
        self._lib.sx_push_chunk(self._store._h, n, _i64p(e), _i64p(k),
                                _i64p(s))

    _EMPTY_POP_EX = PopResult(*(np.empty(0, dtype=np.int64),) * 4,
                              slot_hint=np.empty(0, dtype=np.int32))

    def pop_fired_ex(self, watermark: int) -> PopResult:
        # effective earliest pending end = min(native chunks, the
        # Python-side scalar push buffer the slow path still uses)
        eff_min = min(self._min_pending_end,
                      int(self._lib.sx_min_pending(self._store._h)))
        if watermark < eff_min - 1:
            self.max_fired_watermark = max(self.max_fired_watermark,
                                           watermark)
            return self._EMPTY_POP_EX
        self._drain_fire_buf()  # buf -> one native chunk
        self._min_pending_end = 1 << 62
        t0 = time.perf_counter()
        (keys, starts, ends, sids, slots), (rk, rs, re) = native_pop(
            self._store, watermark)
        self.native_sweep_s += time.perf_counter() - t0
        self.max_fired_watermark = max(self.max_fired_watermark,
                                       watermark)
        if self._multi and len(rk):
            # the base class's reference-shaped walk, with this plane's
            # scalar store accessors (one copy — see _pop_rest_walk)
            ek, es, ee, esid, eslot = self._pop_rest_walk(rk, rs, re)
            if ek:
                keys = np.concatenate([
                    keys, np.asarray(ek, dtype=np.int64)])
                starts = np.concatenate([
                    starts, np.asarray(es, dtype=np.int64)])
                ends = np.concatenate([
                    ends, np.asarray(ee, dtype=np.int64)])
                sids = np.concatenate([
                    sids, np.asarray(esid, dtype=np.int64)])
                slots = np.concatenate([
                    slots, np.asarray(eslot, dtype=np.int32)])
                o = np.argsort(ends, kind="stable")
                keys, starts = keys[o], starts[o]
                ends, sids, slots = ends[o], sids[o], slots[o]
        return PopResult(keys, starts, ends, sids, slots)

    def pop_fired(self, watermark: int):
        r = self.pop_fired_ex(watermark)
        return r.keys, r.starts, r.ends, r.sids

    def _rest_single_lookup(self, key: int) -> int:
        return int(self._lib.sx_lookup1(self._store._h, int(key)))

    def _forget_multi_key(self, key: int) -> None:
        # keep the native multi-membership set mirrored (the sweep
        # classifies against it) — see drop_key_groups
        self._multi.pop(key, None)
        self._lib.sx_multi_remove(self._store._h, int(key))

    def _rest_single_free(self, slot: int) -> int:
        dslot = int(self._store.dslot[slot])
        self._lib.sx_erase1(self._store._h, slot)
        return dslot

    # ------------------------------------------- host-prep sweep helpers

    def shard_group(self, res: AbsorbResult, P: int, maxp: int,
                    key_group_range) -> Tuple:
        """Per-session shard assignment + stable grouping of the LIVE
        sessions by shard, gathering every resolve column in ONE C pass
        (sx_shard_group; the exact keygroups.py formula). Returns
        ``(sess_shard, counts, sorted_idx, key_sorted, sid_sorted,
        fresh_sorted, hint_sorted, row_sorted)`` — the sorted arrays
        slice contiguously per shard."""
        m = len(res.sess_key)
        kg_first, kg_last = (key_group_range
                             if key_group_range is not None else (-1, -1))
        shard = np.empty(m, dtype=np.int64)
        counts = np.empty(int(P), dtype=np.int64)
        sorted_idx = np.empty(m, dtype=np.int64)
        key_s = np.empty(m, dtype=np.int64)
        sid_s = np.empty(m, dtype=np.int64)
        fresh_s = np.empty(m, dtype=np.uint8)
        hint_s = np.empty(m, dtype=np.int32)
        row_s = np.empty(m, dtype=np.int32)
        t0 = time.perf_counter()
        nl = int(self._lib.sx_shard_group(
            m, _i64p(res.sess_key), _i64p(res.sess_sid),
            res.fresh.view(np.uint8).ctypes.data_as(_U8P),
            _i32p(res.slot_hint), _i32p(res.meta_row),
            int(P), int(maxp), int(kg_first), int(kg_last),
            _i64p(shard), _i64p(counts), _i64p(sorted_idx),
            _i64p(key_s), _i64p(sid_s),
            fresh_s.ctypes.data_as(_U8P), _i32p(hint_s), _i32p(row_s)))
        self.native_sweep_s += time.perf_counter() - t0
        if nl < 0:
            raise ValueError(
                "session key routed outside the engine's key-group "
                "range — upstream routing bug")
        return (shard, counts, sorted_idx[:nl], key_s[:nl], sid_s[:nl],
                fresh_s[:nl].view(bool), hint_s[:nl], row_s[:nl])

    def rec_shard_max(self, keys: np.ndarray, P: int, maxp: int,
                      key_group_range) -> int:
        """Max per-shard record count of a batch in one C pass — the
        batch-split working-set bound's cheap first check."""
        kg_first, kg_last = (key_group_range
                             if key_group_range is not None else (-1, -1))
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        t0 = time.perf_counter()
        mx = int(self._lib.sx_rec_shard_max(
            len(keys), _i64p(keys), int(P), int(maxp),
            int(kg_first), int(kg_last)))
        self.native_sweep_s += time.perf_counter() - t0
        if mx < 0:
            raise ValueError(
                "record key routed outside the engine's key-group "
                "range — upstream routing bug")
        return mx

    def route_records(self, n: int, order: np.ndarray,
                      rec_to_sess: np.ndarray, m: int,
                      sorted_idx: np.ndarray, slot_sorted: np.ndarray,
                      sess_shard: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Record routing in one C pass (sx_route):
        ``rec[order[i]] = per_session[rec_to_sess[i]]`` for the slot and
        shard columns, with the resolved slots arriving as the
        (sorted_idx, slot_sorted) pairs the per-shard resolve
        produced."""
        rec_slots = np.empty(n, dtype=np.int32)
        rec_shards = np.empty(n, dtype=np.int64)
        slot_sorted = np.ascontiguousarray(slot_sorted, dtype=np.int32)
        t0 = time.perf_counter()
        self._lib.sx_route(
            int(n), int(m), _i64p(order), _i64p(rec_to_sess),
            len(sorted_idx), _i64p(sorted_idx), _i32p(slot_sorted),
            _i64p(sess_shard), _i32p(rec_slots), _i64p(rec_shards))
        self.native_sweep_s += time.perf_counter() - t0
        return rec_slots, rec_shards

    # --------------------------------------------------------- snapshot

    def restore(self, snap, key_group_filter=None,
                max_parallelism: int = 128) -> None:
        super().restore(snap, key_group_filter=key_group_filter,
                        max_parallelism=max_parallelism)
        # base restore writes multi-interval lists into the dict
        # directly — re-sync the native membership set (the store itself
        # was rebuilt by _reset_store, so the singles side is exact)
        for k in self._multi:
            self._lib.sx_multi_add(self._store._h, int(k))
