from flink_tpu.windowing.aggregates import (
    AccLeaf,
    AggregateFunction,
    SumAggregate,
    CountAggregate,
    MaxAggregate,
    MinAggregate,
    AvgAggregate,
    MultiAggregate,
)
from flink_tpu.windowing.assigners import (
    TumblingEventTimeWindows,
    SlidingEventTimeWindows,
    CumulativeEventTimeWindows,
    EventTimeSessionWindows,
    TumblingProcessingTimeWindows,
    SlidingProcessingTimeWindows,
)

__all__ = [
    "AccLeaf",
    "AggregateFunction",
    "SumAggregate",
    "CountAggregate",
    "MaxAggregate",
    "MinAggregate",
    "AvgAggregate",
    "MultiAggregate",
    "TumblingEventTimeWindows",
    "TumblingProcessingTimeWindows",
    "SlidingProcessingTimeWindows",
    "SlidingEventTimeWindows",
    "CumulativeEventTimeWindows",
    "EventTimeSessionWindows",
]
