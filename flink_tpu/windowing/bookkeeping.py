"""Host-side window lifecycle bookkeeping, shared by the single-device and
mesh-sharded window engines.

Owns the pieces of WindowOperator semantics that are pure host metadata
(reference: streaming/runtime/operators/windowing/WindowOperator.java —
isWindowLate at processElement:293, timer-driven firing at onEventTime:450,
allowed-lateness retention + cleanup timers at clearAllState): the
pending-window heap, the slice cleanup heap, late-record dropping, and the
fire/release ordering on watermark advance. The engines own only the state
arrays and the device math.

Allowed-lateness semantics (mirrors the reference): a window first fires when
the watermark passes its end; its slices are *retained* for ``lateness`` more
event-time ms. A late record landing in a retained slice re-schedules the
already-fired windows it contributes to, producing updated ("late firing")
results — note the vectorized engine re-emits the whole window's keys, not
just the late key. Records whose slices are past retention are dropped.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Set

import numpy as np

from flink_tpu.windowing.assigners import WindowAssigner

_NEG_INF = -(1 << 62)


class SliceBookkeeper:
    def __init__(self, assigner: WindowAssigner, allowed_lateness: int = 0):
        self.assigner = assigner
        self.allowed_lateness = allowed_lateness
        self._pending: List[int] = []
        self._pending_set: Set[int] = set()
        # slice end -> last participating window end (live slices)
        self._slice_last_window: Dict[int, int] = {}
        # (cleanup_time, slice_end): slice freed when watermark >= cleanup_time
        self._cleanup: List[tuple] = []
        self.watermark: int = _NEG_INF
        self.max_fired_end: int = _NEG_INF
        self.late_records_dropped = 0

    # ---------------------------------------------------------------- arrivals

    def live_mask(self, slice_ends: np.ndarray) -> Optional[np.ndarray]:
        """Late-record filter: a record is dropped iff its slice is past
        retention (last window end - 1 + lateness <= current watermark).
        Returns a boolean mask if any record must be dropped, else None."""
        if self.watermark <= _NEG_INF // 2:
            return None
        # scalar early-out: the OLDEST slice in the batch decides whether a
        # full vectorized pass is needed at all — for in-order streams the
        # oldest slice is always live, so the common case costs one .min()
        # instead of three passes over the batch
        oldest = int(np.asarray(slice_ends).min())
        oldest_last = int(self.assigner.last_window_ends(
            np.asarray([oldest], dtype=np.int64))[0])
        if oldest_last - 1 + self.allowed_lateness > self.watermark:
            return None
        last_ends = self.assigner.last_window_ends(slice_ends)
        live = last_ends - 1 + self.allowed_lateness > self.watermark
        dropped = len(live) - int(live.sum())
        if dropped == 0:
            return None
        self.late_records_dropped += dropped
        return live

    def register_slices(self, slice_ends: np.ndarray,
                        uniq: Optional[np.ndarray] = None) -> None:
        """Track new slices and (re-)schedule their windows.

        A window is scheduled iff it can still produce output:
        w - 1 + lateness > watermark. For an already-fired window inside the
        lateness allowance this is a late re-firing. ``uniq`` lets the
        caller supply the already-computed distinct slice ends (see
        WindowAssigner.slice_plan) instead of re-sorting the batch."""
        lateness = self.allowed_lateness
        if uniq is None:
            uniq = np.unique(slice_ends)
        for se in uniq.tolist():
            ends = None
            if se not in self._slice_last_window:
                ends = self.assigner.window_ends_for_slice(se)
                last = ends[-1]
                self._slice_last_window[se] = last
                heapq.heappush(self._cleanup, (last - 1 + lateness, se))
            elif lateness > 0:
                # existing slice: a late record may need to re-fire windows
                # that already fired
                ends = self.assigner.window_ends_for_slice(se)
            if ends is None:
                continue
            for w in ends:
                if (w - 1 + lateness > self.watermark
                        and w not in self._pending_set):
                    self._pending_set.add(w)
                    heapq.heappush(self._pending, w)

    # -------------------------------------------------------------------- fire

    def pending_windows(self) -> Set[int]:
        """Window ends currently scheduled to fire (a read-only view of
        the live set — do not mutate) — the set the pane pre-aggregation
        keeps a running partial row for (windowing/windower.py
        PaneWindower; includes late re-registrations). Consumers needing
        deterministic order sort it themselves (rebuild_window_partials
        does)."""
        return self._pending_set

    def next_window(self, watermark: int) -> Optional[int]:
        """Pop the next window due at ``watermark`` (end-1 <= watermark)."""
        self.watermark = max(self.watermark, watermark)
        if self._pending and self._pending[0] - 1 <= watermark:
            w_end = heapq.heappop(self._pending)
            self._pending_set.discard(w_end)
            return w_end
        return None

    def mark_fired(self, window_end: int) -> None:
        self.max_fired_end = max(self.max_fired_end, window_end)

    def expired_slices(self, watermark: int) -> List[int]:
        """Slices past retention at ``watermark`` — free their state.
        Call after the fire loop of the same watermark."""
        self.watermark = max(self.watermark, watermark)
        out: List[int] = []
        while self._cleanup and self._cleanup[0][0] <= watermark:
            _, se = heapq.heappop(self._cleanup)
            if se in self._slice_last_window:
                del self._slice_last_window[se]
                out.append(se)
        return out

    # ---------------------------------------------------------------- snapshot

    def snapshot(self) -> Dict[str, object]:
        return {
            "pending": sorted(self._pending),
            "slice_last_window": dict(self._slice_last_window),
            "watermark": self.watermark,
            "max_fired_end": self.max_fired_end,
            "late_records_dropped": self.late_records_dropped,
        }

    def merge_restore(self, snap: Dict[str, object]) -> None:
        """Partial-failover merge: fold a CHECKPOINT-time book into the
        LIVE book so a lost shard's key groups can replay their range.

        Rules (window metadata is global, unlike the per-key state):

        - registered slices = UNION — slices created by survivors after
          the checkpoint stay tracked; slices the checkpoint knew that
          already expired here re-register (their replayed re-fire emits
          only the restored range's keys: the survivors' rows are gone).
        - pending windows = UNION of live pending and the checkpoint's
          pending + every window of a re-registered slice that can still
          produce output AT THE CHECKPOINT watermark — a window fired
          between the checkpoint and the failure must RE-FIRE during
          replay (its restored-range rows were rolled back), and emits
          nothing for survivors (their slots were freed at the original
          fire).
        - watermark = the CHECKPOINT's — replayed records must pass the
          late-record guard exactly as they did originally; survivors
          are unaffected because replay feeds only the restored range,
          and the watermark monotonically re-advances with the replayed
          sequence.
        """
        self._slice_last_window.update(
            dict(snap.get("slice_last_window", {})))
        self._cleanup = [
            (last - 1 + self.allowed_lateness, se)
            for se, last in self._slice_last_window.items()
        ]
        heapq.heapify(self._cleanup)
        ckpt_wm = snap.get("watermark", snap.get("max_fired_end",
                                                 _NEG_INF))
        lateness = self.allowed_lateness
        for w in snap.get("pending", []):
            if w not in self._pending_set:
                self._pending_set.add(w)
                heapq.heappush(self._pending, w)
        # windows fired AFTER the checkpoint: pending in neither book,
        # but their slices are registered — re-schedule every window
        # still fireable at the checkpoint watermark
        for se in self._slice_last_window:
            for w in self.assigner.window_ends_for_slice(se):
                if (w - 1 + lateness > ckpt_wm
                        and w not in self._pending_set):
                    self._pending_set.add(w)
                    heapq.heappush(self._pending, w)
        self.watermark = ckpt_wm
        self.max_fired_end = min(
            self.max_fired_end,
            int(snap.get("max_fired_end", _NEG_INF)))

    def restore(self, snap: Dict[str, object]) -> None:
        # empty sub-structures may be pruned by the checkpoint codec
        self._pending = list(snap.get("pending", []))
        heapq.heapify(self._pending)
        self._pending_set = set(self._pending)
        self._slice_last_window = dict(snap.get("slice_last_window", {}))
        self._cleanup = [
            (last - 1 + self.allowed_lateness, se)
            for se, last in self._slice_last_window.items()
        ]
        heapq.heapify(self._cleanup)
        self.watermark = snap.get("watermark", snap.get("max_fired_end",
                                                        _NEG_INF))
        self.max_fired_end = snap.get("max_fired_end", _NEG_INF)
        self.late_records_dropped = snap.get("late_records_dropped", 0)
