"""Host-side window lifecycle bookkeeping, shared by the single-device and
mesh-sharded window engines.

Owns the pieces of WindowOperator semantics that are pure host metadata
(reference: streaming/runtime/operators/windowing/WindowOperator.java —
isWindowLate handling at processElement:293, timer-driven firing at
onEventTime:450, state cleanup at clearAllState): the pending-window heap,
slice -> last-window registry, late-record dropping, and the
fire/release ordering on watermark advance. The engines own only the state
arrays and the device math.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Set

import numpy as np

from flink_tpu.windowing.assigners import WindowAssigner

_NEG_INF = -(1 << 62)


class SliceBookkeeper:
    def __init__(self, assigner: WindowAssigner, allowed_lateness: int = 0):
        self.assigner = assigner
        self.allowed_lateness = allowed_lateness
        self._pending: List[int] = []
        self._pending_set: Set[int] = set()
        self._slice_last_window: Dict[int, int] = {}
        self._free_after: Dict[int, List[int]] = {}
        self.max_fired_end: int = _NEG_INF
        self.late_records_dropped = 0

    # ---------------------------------------------------------------- arrivals

    def live_mask(self, slice_ends: np.ndarray) -> Optional[np.ndarray]:
        """Late-record filter: a record is late iff every window of its slice
        already fired (allowing ``allowed_lateness``). Returns a boolean mask
        if any record must be dropped, else None."""
        if self.max_fired_end <= _NEG_INF // 2:
            return None
        horizon = self.max_fired_end - self.allowed_lateness
        last_ends = slice_ends + self.assigner.size - self.assigner.slice_width
        live = last_ends > horizon
        dropped = len(live) - int(live.sum())
        if dropped == 0:
            return None
        self.late_records_dropped += dropped
        return live

    def register_slices(self, slice_ends: np.ndarray) -> None:
        """Track new slices and schedule their windows."""
        for se in np.unique(slice_ends).tolist():
            if se not in self._slice_last_window:
                ends = self.assigner.window_ends_for_slice(se)
                last = ends[-1]
                self._slice_last_window[se] = last
                self._free_after.setdefault(last, []).append(se)
                for w in ends:
                    if w > self.max_fired_end and w not in self._pending_set:
                        self._pending_set.add(w)
                        heapq.heappush(self._pending, w)

    # -------------------------------------------------------------------- fire

    def next_window(self, watermark: int) -> Optional[int]:
        """Pop the next window due at ``watermark`` (end-1 <= watermark)."""
        if self._pending and self._pending[0] - 1 <= watermark:
            w_end = heapq.heappop(self._pending)
            self._pending_set.discard(w_end)
            return w_end
        return None

    def mark_fired(self, window_end: int) -> List[int]:
        """Record the fire; returns slice ends that can now be freed."""
        self.max_fired_end = max(self.max_fired_end, window_end)
        ends = self._free_after.pop(window_end, None)
        if not ends:
            return []
        for se in ends:
            self._slice_last_window.pop(se, None)
        return ends

    # ---------------------------------------------------------------- snapshot

    def snapshot(self) -> Dict[str, object]:
        return {
            "pending": sorted(self._pending),
            "slice_last_window": dict(self._slice_last_window),
            "max_fired_end": self.max_fired_end,
            "late_records_dropped": self.late_records_dropped,
        }

    def restore(self, snap: Dict[str, object]) -> None:
        self._pending = list(snap["pending"])
        heapq.heapify(self._pending)
        self._pending_set = set(self._pending)
        self._slice_last_window = dict(snap["slice_last_window"])
        self._free_after = {}
        for se, last in self._slice_last_window.items():
            self._free_after.setdefault(last, []).append(se)
        self.max_fired_end = snap["max_fired_end"]
        self.late_records_dropped = snap.get("late_records_dropped", 0)
