"""Host-side session interval metadata, shared by the single-device and
mesh-sharded session engines.

reference: MergingWindowSet + WindowOperator.java:159-162 — merge *metadata*
(tiny per-key interval lists) lives apart from merged *state* (accumulator
slots). This module is the metadata half; a device engine supplies the state
half (slot resolution + merge/scatter/fire kernels).

Key property exploited by the mesh engine: sessions are per-key and keys are
owned by exactly one shard (key-group routing), so session merging NEVER
crosses shards — the metadata is engine-global, only slot residency is
sharded.

Columnar store (round 5): the clickstream shape holds ~one live session
per key across millions of keys, and a dict of per-key interval lists
priced every operation at a Python allocation. The store is now hybrid:

- **singles** (the overwhelming case): a slot index (the same native
  hash map the state plane uses) maps key -> slot into dense
  ``start/end/sid`` arrays. Registration, overlap-extend, fire
  validation, and removal are all vectorized batch operations.
- **multi**: keys holding >= 2 concurrently-live sessions fall back to
  the reference-shaped interval lists (``key -> [(start, end, sid)]``)
  — exact merge semantics, including accumulator merge groups.

A key lives in exactly one of the two stores; promotion/demotion happens
in the slow path that needed it.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.state.slot_table import make_slot_index

_NEG_INF = -(1 << 62)


class NativePlaneError(RuntimeError):
    """A native (C) metadata sweep failed at runtime. Engines catch
    this at the one point where no device/state mutation has happened
    yet (the absorb is the batch's first mutation) and fall back to the
    bit-identical Python plane — once, loudly — instead of crashing the
    batch (see MeshSessionEngine._meta_fallback)."""


@dataclasses.dataclass
class AbsorbResult:
    """One absorbed batch, engine-facing: the classic absorb_batch tuple
    plus the per-session columns the state-plane resolve consumes.

    ``fresh``: sessions CREATED by this absorb that cannot be resident
    or paged in the state plane (skip the hash probe AND the page
    query). ``slot_hint``: the folded device slot from the metadata row
    (-1 unknown) — engines VERIFY a hint against the state table's own
    metadata before trusting it, so a stale fold costs a fallback
    probe, never a wrong row."""

    sess_key: np.ndarray
    sess_sid: np.ndarray
    rec_to_sess: np.ndarray
    order: np.ndarray
    groups: List["MergeGroup"]
    #: None when the caller opted out (want_fresh=False — only the
    #: paged resolve reads it)
    fresh: Optional[np.ndarray]
    slot_hint: Optional[np.ndarray] = None
    #: native plane: each fast-path session's metadata row, -1 for
    #: slow/stale sessions — lets note_slots fold by direct array
    #: scatter instead of a hash pass
    meta_row: Optional[np.ndarray] = None


@dataclasses.dataclass
class PopResult:
    """One watermark pop: fired sessions as columnar int64 arrays in end
    order, plus the folded device slot per fired session (-1 unknown;
    only the native plane folds)."""

    keys: np.ndarray
    starts: np.ndarray
    ends: np.ndarray
    sids: np.ndarray
    slot_hint: Optional[np.ndarray] = None


@dataclasses.dataclass
class MergeGroup:
    """A chain-free batch of accumulator merges: within one group no sid is
    both a source and a destination, so a single gather/scatter kernel is
    safe. Groups must execute in order."""

    keys_dst: List[int] = dataclasses.field(default_factory=list)
    sids_dst: List[int] = dataclasses.field(default_factory=list)
    keys_src: List[int] = dataclasses.field(default_factory=list)
    sids_src: List[int] = dataclasses.field(default_factory=list)
    absorbed_sids: List[int] = dataclasses.field(default_factory=list)

    def __len__(self) -> int:
        return len(self.sids_dst)


class _SessionsView:
    """Read-only dict-like view over the hybrid store — keeps the
    ``meta.sessions`` surface (query paths, tests) unchanged."""

    def __init__(self, meta: "SessionIntervalSet"):
        self._m = meta

    def get(self, key, default=None):
        ivs = self._m._intervals_of(int(key))
        return ivs if ivs is not None else default

    def __getitem__(self, key):
        ivs = self._m._intervals_of(int(key))
        if ivs is None:
            raise KeyError(key)
        return ivs

    def __contains__(self, key) -> bool:
        return self._m._intervals_of(int(key)) is not None

    def __len__(self) -> int:
        return int(self._m._idx.num_used) + len(self._m._multi)

    def items(self):
        m = self._m
        used = m._idx.used_slots()
        keys = m._idx.slot_key[used]
        for k, s, e, sid in zip(keys.tolist(),
                                m._s_start[used].tolist(),
                                m._s_end[used].tolist(),
                                m._s_sid[used].tolist()):
            yield int(k), [(int(s), int(e), int(sid))]
        for k, ivs in m._multi.items():
            yield int(k), list(ivs)

    def keys(self):
        for k, _ in self.items():
            yield k


class SessionIntervalSet:
    """Per-key session intervals + lazy fire candidates + sid allocator."""

    def __init__(self, gap: int, allowed_lateness: int = 0):
        self.gap = int(gap)
        self.allowed_lateness = int(allowed_lateness)
        #: time spent inside the native sweep calls (absorb + pop); the
        #: pure-Python plane keeps it at 0.0 — bench tooling reports it
        #: as its own host-prep line
        self.native_sweep_s = 0.0
        #: keys with >= 2 live sessions: reference-shaped interval lists
        self._multi: Dict[int, List[Tuple[int, int, int]]] = {}
        self._reset_store()
        self._next_sid = 1
        #: fire candidates as COLUMNAR chunks
        #: [(ends, keys, sids, lo, hi), ...] with cached per-chunk
        #: end bounds — pushes are array appends, and the watermark cut
        #: touches only chunks the watermark actually reached: a chunk
        #: wholly due pops whole, a chunk wholly pending is SKIPPED
        #: untouched. Event time advances chunk by chunk, so a pop is
        #: O(due + one straddler), never O(live candidates) — the old
        #: single-merged-chunk layout re-masked and re-copied the whole
        #: ~live-session-sized pool on every watermark advance.
        self._fire_chunks: List[Tuple[np.ndarray, np.ndarray,
                                      np.ndarray, int, int]] = []
        #: scalar push buffers (slow-path merges), drained into a chunk
        #: — three parallel component lists, NOT a list of tuples (the
        #: drain builds columns; np.asarray over tuples walked every
        #: element twice)
        self._fire_buf: Tuple[List[int], List[int], List[int]] = \
            ([], [], [])
        #: earliest pending candidate end — pop_fired returns O(1) when
        #: the watermark has not reached it (the heap's cheap peek)
        self._min_pending_end = 1 << 62
        self.max_fired_watermark = _NEG_INF
        self.late_records_dropped = 0
        # merge-group accumulation during absorb_batch
        self._groups: List[MergeGroup] = []
        self._cur: Optional[MergeGroup] = None
        self._cur_dst: set = set()
        self._cur_src: set = set()

    def _reset_store(self) -> None:
        """(Re)create the empty singles store — the ONE hook the native
        plane overrides to swap the numpy arrays for the C views."""
        self._idx = make_slot_index(1 << 16, on_grow=self._on_grow,
                                    track_namespaces=False)
        cap = self._idx.capacity
        self._s_start = np.zeros(cap, dtype=np.int64)
        self._s_end = np.zeros(cap, dtype=np.int64)
        self._s_sid = np.zeros(cap, dtype=np.int64)
        self._multi.clear()

    def _on_grow(self, old: int, new: int) -> None:
        for name in ("_s_start", "_s_end", "_s_sid"):
            arr = getattr(self, name)
            grown = np.zeros(new, dtype=np.int64)
            grown[:old] = arr
            setattr(self, name, grown)

    # --------------------------------------------------------- store access

    @property
    def sessions(self) -> _SessionsView:
        return _SessionsView(self)

    @property
    def sid_watermark(self) -> int:
        """Next session id the allocator will hand out — sids are
        monotonic, so a sid >= the pre-absorb watermark marks a session
        CREATED by that absorb (engines use this to skip state-plane
        probes for sessions that cannot exist there yet)."""
        return self._next_sid

    def _intervals_of(self, key: int
                      ) -> Optional[List[Tuple[int, int, int]]]:
        ivs = self._multi.get(key)
        if ivs is not None:
            return ivs
        a = np.asarray([key], dtype=np.int64)
        slot = int(self._idx.lookup(a, a)[0])
        if slot < 0:
            return None
        return [(int(self._s_start[slot]), int(self._s_end[slot]),
                 int(self._s_sid[slot]))]

    def _store_intervals(self, key: int,
                         ivs: List[Tuple[int, int, int]]) -> None:
        """Write a key's (possibly merged) interval list back to the
        hybrid store, moving it between singles and multi as needed."""
        a = np.asarray([key], dtype=np.int64)
        slot = int(self._idx.lookup(a, a)[0])
        if len(ivs) == 1:
            self._multi.pop(key, None)
            if slot < 0:
                slot = int(self._idx.lookup_or_insert(a, a)[0])
            s, e, sid = ivs[0]
            self._s_start[slot] = s
            self._s_end[slot] = e
            self._s_sid[slot] = sid
        else:
            if slot >= 0:
                self._idx.free_slots(np.asarray([slot], dtype=np.int32))
            ivs.sort()
            self._multi[key] = ivs

    # ------------------------------------------------------- fire pending

    def _push_fire(self, end: int, key: int, sid: int) -> None:
        ends, keys, sids = self._fire_buf
        ends.append(end)
        keys.append(key)
        sids.append(sid)
        if end < self._min_pending_end:
            self._min_pending_end = end

    def _push_fires(self, ends: np.ndarray, keys: np.ndarray,
                    sids: np.ndarray) -> None:
        if len(ends):
            ends = np.asarray(ends, dtype=np.int64)
            lo = int(ends.min())
            self._fire_chunks.append((
                ends,
                np.asarray(keys, dtype=np.int64),
                np.asarray(sids, dtype=np.int64),
                lo, int(ends.max())))
            if lo < self._min_pending_end:
                self._min_pending_end = lo

    def _drain_fire_buf(self) -> None:
        if self._fire_buf[0]:
            ends, keys, sids = self._fire_buf
            self._fire_buf = ([], [], [])
            self._push_fires(np.asarray(ends, dtype=np.int64),
                             np.asarray(keys, dtype=np.int64),
                             np.asarray(sids, dtype=np.int64))

    # ---------------------------------------------------------------- absorb

    def absorb_batch(self, keys: np.ndarray, ts: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray, List[MergeGroup]]:
        """Sessionize a batch and merge it into the interval set.

        Returns ``(sess_key, sess_sid, rec_to_sess, order, merge_groups)``:
        per batch-local session its key and merged sid (-1 = stale on
        arrival, see below), the sorted-order record->session indirection,
        the lexsort order itself, and the accumulator merges the metadata
        merge implied. Records of a stale session must be dropped (counted
        in ``late_records_dropped`` by the caller via the -1 marker).

        Lateness is decided per *merged session*, not per record — an
        out-of-order record that merges into a live session is never late
        (reference: WindowOperator merges first, then isWindowLate).
        """
        n = len(keys)
        # vectorized batch-local sessionization: sort by (key, ts); a new
        # local session starts at a key change or a gap exceedance.
        # When the batch's time span fits the spare bits of an int64 the
        # two-key lexsort collapses into ONE argsort of a packed
        # (key << span_bits) | (ts - ts_min) column — measurably cheaper
        # at micro-batch sizes, and every realistic micro-batch spans
        # seconds, not years
        t_min = int(ts.min()) if n else 0
        span = (int(ts.max()) - t_min) if n else 0
        k_min = int(keys.min()) if n else 0
        k_max = int(keys.max()) if n else 0
        shift = max(span.bit_length(), 1)
        # shift <= 62 guards the span itself: a pathological range
        # (sentinel timestamps) must take the lexsort fallback, not a
        # negative-shift ValueError
        if n and shift <= 62 and k_min >= 0 \
                and (k_max >> (62 - shift)) == 0:
            packed = (keys.astype(np.int64) << shift) | \
                (ts.astype(np.int64) - t_min)
            order = np.argsort(packed, kind="stable")
        else:
            order = np.lexsort((ts, keys))
        ks, tss = keys[order], ts[order]
        new_sess = np.empty(n, dtype=bool)
        new_sess[0] = True
        new_sess[1:] = (ks[1:] != ks[:-1]) | (tss[1:] - tss[:-1] > self.gap)
        rec_to_sess = np.cumsum(new_sess) - 1
        starts_pos = np.nonzero(new_sess)[0]
        m = len(starts_pos)
        ends_pos = np.empty(m, dtype=np.int64)
        ends_pos[:-1] = starts_pos[1:] - 1
        ends_pos[-1] = n - 1
        sess_key = ks[starts_pos]
        sess_min = tss[starts_pos]
        sess_max = tss[ends_pos]

        self._groups, self._cur = [], None
        self._cur_dst, self._cur_src = set(), set()
        sess_sid = np.empty(m, dtype=np.int64)
        ends_all = sess_max + self.gap

        if self.max_fired_watermark > _NEG_INF // 2:
            stale = (ends_all - 1 + self.allowed_lateness
                     <= self.max_fired_watermark)
        else:
            stale = np.zeros(m, dtype=bool)

        first_of_key = np.empty(m, dtype=bool)
        first_of_key[0] = True
        first_of_key[1:] = sess_key[1:] != sess_key[:-1]
        only_of_key = first_of_key.copy()
        only_of_key[:-1] &= first_of_key[1:]

        slots = self._idx.lookup(sess_key, sess_key)
        found = slots >= 0
        in_multi = np.zeros(m, dtype=bool)
        if self._multi:
            probe = ~found
            if probe.any():
                pk = sess_key[probe]
                in_multi[probe] = np.fromiter(
                    (int(k) in self._multi for k in pk.tolist()),
                    np.bool_, len(pk))

        # A: fresh singles (no stored state) — bulk registration
        fast = only_of_key & ~found & ~in_multi
        fresh_stale = fast & stale
        fast &= ~stale
        cnt = int(fast.sum())
        if cnt:
            sids_fast = np.arange(self._next_sid, self._next_sid + cnt,
                                  dtype=np.int64)
            self._next_sid += cnt
            sess_sid[fast] = sids_fast
            fk = sess_key[fast]
            fslots = self._idx.lookup_or_insert(fk, fk)
            self._s_start[fslots] = sess_min[fast]
            self._s_end[fslots] = ends_all[fast]
            self._s_sid[fslots] = sids_fast
            self._push_fires(ends_all[fast], fk, sids_fast)
        sess_sid[fresh_stale] = -1  # stale on arrival (never stored)

        # B: sole local session meeting a stored SINGLE — vectorized
        # overlap-extend; disjoint ones (a second live session) and
        # everything multi-flavored go to the exact slow path
        b = only_of_key & found
        slow_extra = None
        if b.any():
            bi = np.nonzero(b)[0]
            bs = slots[bi]
            ex_s = self._s_start[bs]
            ex_e = self._s_end[bs]
            ov = (sess_min[bi] <= ex_e) & (ex_s <= ends_all[bi])
            b1 = bi[ov]
            if len(b1):
                s1 = slots[b1]
                ns_ = np.minimum(self._s_start[s1], sess_min[b1])
                ne_ = np.maximum(self._s_end[s1], ends_all[b1])
                changed = ne_ != self._s_end[s1]
                self._s_start[s1] = ns_
                self._s_end[s1] = ne_
                sess_sid[b1] = self._s_sid[s1]
                if changed.any():
                    self._push_fires(ne_[changed],
                                     sess_key[b1][changed],
                                     self._s_sid[s1][changed])
            slow_extra = bi[~ov]

        # slow path: multi-flavored rows (everything not covered above)
        # plus B2 (disjoint second sessions), in ascending (key, ts) order
        covered = fast | fresh_stale | b
        slow = np.nonzero(~covered)[0]
        if slow_extra is not None and len(slow_extra):
            slow = np.sort(np.concatenate([slow, slow_extra]))
        for j in slow:
            sess_sid[j] = self._merge_session(
                int(sess_key[j]), int(sess_min[j]), int(ends_all[j]))
        groups = self._groups
        if self._cur is not None and len(self._cur):
            groups.append(self._cur)
        self._groups, self._cur = [], None
        return sess_key, sess_sid, rec_to_sess, order, groups

    def absorb_batch_ex(self, keys: np.ndarray, ts: np.ndarray,
                        want_fresh: bool = True) -> AbsorbResult:
        """absorb_batch plus the per-session resolve columns engines
        consume: the fresh mask (sids allocated by THIS absorb, minus
        merge destinations — a fresh dst was already inserted by its
        merge group, and skipping its probe would leave it
        eviction-unprotected inside the very resolve that follows) and,
        on the native plane, the folded device-slot hints.

        ``want_fresh=False`` skips the fresh-mask derivation (the
        unique/isin over merge destinations) — only the PAGED resolve
        reads it, and this sits on the per-batch hot path."""
        sid_floor = self.sid_watermark
        sess_key, sess_sid, rec_to_sess, order, groups = \
            self.absorb_batch(keys, ts)
        fresh = None
        if want_fresh:
            fresh = sess_sid >= sid_floor
            if groups:
                merged_dst = np.unique(np.concatenate(
                    [np.asarray(g.sids_dst, dtype=np.int64)
                     for g in groups]))
                if len(merged_dst):
                    fresh &= ~np.isin(sess_sid, merged_dst)
        return AbsorbResult(sess_key, sess_sid, rec_to_sess, order,
                            groups, fresh)

    def note_slots(self, keys: np.ndarray, sids: np.ndarray,
                   slots: np.ndarray, rows=None) -> None:
        """Fold resolved device slots back into the metadata rows so the
        NEXT batch's resolve can skip the state-plane hash probe.
        ``rows``: the sessions' metadata rows when the caller holds them
        (AbsorbResult.meta_row) — fold by direct scatter, no hash pass.
        The pure-Python plane does not fold (its resolve is the
        reference path) — no-op."""

    def _add_merge(self, key: int, dst_sid: int, src_sid: int) -> None:
        """Queue an accumulator merge. A chain (src was an earlier dst, or
        dst was an earlier src) would make a single gather/scatter kernel
        read stale values, so it closes the current group."""
        if self._cur is None:
            self._cur = MergeGroup()
        elif (src_sid in self._cur_dst or src_sid in self._cur_src
                or dst_sid in self._cur_src):
            self._groups.append(self._cur)
            self._cur = MergeGroup()
            self._cur_dst, self._cur_src = set(), set()
        g = self._cur
        g.keys_dst.append(key)
        g.sids_dst.append(dst_sid)
        g.keys_src.append(key)
        g.sids_src.append(src_sid)
        g.absorbed_sids.append(src_sid)
        self._cur_dst.add(dst_sid)
        self._cur_src.add(src_sid)

    def _merge_session(self, key: int, start: int, end: int) -> int:
        """Merge [start, end) into key's intervals; returns the session id,
        or -1 if the session is stale on arrival. Mirrors
        MergingWindowSet.addWindow: overlapping intervals collapse into
        one; absorbed sessions queue an accumulator merge."""
        intervals = self._intervals_of(key)
        if intervals is None:
            if self._stale(end):
                return -1
            sid = self._alloc_sid()
            self._store_intervals(key, [(start, end, sid)])
            self._push_fire(end, key, sid)
            return sid

        overlapping = [iv for iv in intervals
                       if iv[0] <= end and start <= iv[1]]
        if not overlapping:
            if self._stale(end):
                return -1
            sid = self._alloc_sid()
            intervals.append((start, end, sid))
            self._store_intervals(key, intervals)
            self._push_fire(end, key, sid)
            return sid

        # absorb into the first overlapping interval's session
        keep = overlapping[0]
        new_start = min(start, keep[0])
        new_end = max(end, keep[1])
        for iv in overlapping[1:]:
            new_start = min(new_start, iv[0])
            new_end = max(new_end, iv[1])
            self._add_merge(key, keep[2], iv[2])
        remaining = [iv for iv in intervals if iv not in overlapping]
        remaining.append((new_start, new_end, keep[2]))
        self._store_intervals(key, remaining)
        if new_end != keep[1]:
            self._push_fire(new_end, key, keep[2])
        return keep[2]

    def _stale(self, end: int) -> bool:
        return (self.max_fired_watermark > _NEG_INF // 2
                and end - 1 + self.allowed_lateness
                <= self.max_fired_watermark)

    def _alloc_sid(self) -> int:
        sid = self._next_sid
        self._next_sid += 1
        return sid

    # ------------------------------------------------------------------ fire

    _EMPTY_POP = (np.empty(0, dtype=np.int64),) * 4

    def pop_fired_ex(self, watermark: int) -> PopResult:
        """pop_fired plus the fired sessions' folded device slots (the
        native plane's pop carries them out of the metadata rows; here
        they are unknown)."""
        keys, starts, ends, sids = self.pop_fired(watermark)
        return PopResult(keys, starts, ends, sids)

    def pop_fired(self, watermark: int
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                             np.ndarray]:
        """All sessions whose end - 1 <= watermark, removed from the set.
        Returns int64 ARRAYS (keys, starts, ends, sids) in end order —
        the fire paths are columnar, and a list round-trip here cost a
        tolist + re-asarray of every fired session. Stale candidates
        (merged or extended sessions) are skipped lazily — one vectorized
        watermark cut selects the due candidates, one vectorized
        (sid, end) compare validates the single-store ones; only
        multi-key candidates walk interval lists."""
        if watermark < self._min_pending_end - 1:
            # nothing can be due yet — O(1), the heap's cheap peek
            self.max_fired_watermark = max(self.max_fired_watermark,
                                           watermark)
            return self._EMPTY_POP
        self._drain_fire_buf()
        if not self._fire_chunks:
            self._min_pending_end = 1 << 62
            self.max_fired_watermark = max(self.max_fired_watermark,
                                           watermark)
            return self._EMPTY_POP
        # chunk-bounded watermark cut: whole chunks pop or stay by their
        # cached [lo, hi] end bounds; only STRADDLING chunks pay a mask
        due_parts: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        kept: List[Tuple[np.ndarray, np.ndarray, np.ndarray, int, int]] \
            = []
        min_pending = 1 << 62
        for chunk in self._fire_chunks:
            ends, keys, sids, lo, hi = chunk
            if hi - 1 <= watermark:          # wholly due
                due_parts.append((ends, keys, sids))
            elif lo - 1 > watermark:         # wholly pending: untouched
                kept.append(chunk)
                min_pending = min(min_pending, lo)
            else:                            # straddler
                due = ends - 1 <= watermark
                due_parts.append((ends[due], keys[due], sids[due]))
                keep = ~due
                k_ends = ends[keep]
                k_lo = int(k_ends.min())
                kept.append((k_ends, keys[keep], sids[keep],
                             k_lo, int(k_ends.max())))
                min_pending = min(min_pending, k_lo)
        self._fire_chunks = kept
        self._min_pending_end = min_pending
        if due_parts:
            if len(due_parts) > 1:
                d_ends = np.concatenate([c[0] for c in due_parts])
                d_keys = np.concatenate([c[1] for c in due_parts])
                d_sids = np.concatenate([c[2] for c in due_parts])
            else:
                d_ends, d_keys, d_sids = due_parts[0]
            order = np.argsort(d_ends, kind="stable")  # heap pop order
            d_ends, d_keys, d_sids = (d_ends[order], d_keys[order],
                                      d_sids[order])
        else:
            d_ends = d_keys = d_sids = np.empty(0, dtype=np.int64)
        self.max_fired_watermark = max(self.max_fired_watermark, watermark)
        if not len(d_ends):
            return self._EMPTY_POP

        slots = self._idx.lookup(d_keys, d_keys)
        sing = slots >= 0
        valid = sing.copy()
        if sing.any():
            vs = slots[sing]
            valid[sing] = ((self._s_sid[vs] == d_sids[sing])
                           & (self._s_end[vs] == d_ends[sing]))
        out_keys = d_keys[valid]
        out_starts = self._s_start[slots[valid]]
        out_ends = d_ends[valid]
        out_sids = d_sids[valid]
        if valid.any():
            # the pair columns are in hand (key == ns for the meta
            # index) — skip free_slots' per-slot metadata gathers
            self._idx.free_slots(slots[valid].astype(np.int32),
                                 keys=out_keys, nss=out_keys)

        rest = np.nonzero(~sing)[0]
        if self._multi and len(rest):
            ek, es, ee, esid, _ = self._pop_rest_walk(
                d_keys[rest], d_sids[rest], d_ends[rest])
            if ek:
                out_keys = np.concatenate([
                    out_keys, np.asarray(ek, dtype=np.int64)])
                out_starts = np.concatenate([
                    out_starts, np.asarray(es, dtype=np.int64)])
                out_ends = np.concatenate([
                    out_ends, np.asarray(ee, dtype=np.int64)])
                out_sids = np.concatenate([
                    out_sids, np.asarray(esid, dtype=np.int64)])
                o = np.argsort(out_ends, kind="stable")
                out_keys, out_starts = out_keys[o], out_starts[o]
                out_ends, out_sids = out_ends[o], out_sids[o]
        return (out_keys, np.asarray(out_starts, dtype=np.int64),
                out_ends, out_sids)

    def _pop_rest_walk(self, rk, rs, re_):
        """Validate REST candidates — keys absent from the singles
        store at cut time — against the multi-interval lists; the ONE
        copy of the reference-shaped walk both planes run (the native
        plane only swaps the scalar store accessors via the two hooks
        below). Returns columnar extras ``(keys, starts, ends, sids,
        slots)`` — slots are the folded device slots where known."""
        ek: List[int] = []
        es: List[int] = []
        ee: List[int] = []
        esid: List[int] = []
        eslot: List[int] = []
        for j in range(len(rk)):
            key = int(rk[j])
            sid, end = int(rs[j]), int(re_[j])
            ivs = self._multi.get(key)
            if not ivs:
                # the key may have demoted to the single store earlier
                # in THIS pop (a sibling session fired and left exactly
                # one) — validate there
                slot = self._rest_single_lookup(key)
                if (slot >= 0 and self._s_sid[slot] == sid
                        and self._s_end[slot] == end):
                    ek.append(key)
                    es.append(int(self._s_start[slot]))
                    ee.append(end)
                    esid.append(sid)
                    eslot.append(self._rest_single_free(slot))
                continue
            cur = next((iv for iv in ivs if iv[2] == sid), None)
            if cur is None or cur[1] != end:
                continue
            ek.append(key)
            es.append(cur[0])
            ee.append(end)
            esid.append(sid)
            eslot.append(-1)
            ivs.remove(cur)
            if len(ivs) == 1:
                del self._multi[key]
                self._store_intervals(key, ivs)
        return ek, es, ee, esid, eslot

    def _rest_single_lookup(self, key: int) -> int:
        """Store row of ``key`` in the singles store, -1 if absent."""
        a = np.asarray([key], dtype=np.int64)
        return int(self._idx.lookup(a, a)[0])

    def _rest_single_free(self, slot: int) -> int:
        """Free a validated demoted-single row; returns its folded
        device slot (-1 on this plane — it does not fold)."""
        self._idx.free_slots(np.asarray([slot], dtype=np.int32))
        return -1

    # -------------------------------------------------------------- snapshot

    def snapshot(self) -> Dict[str, object]:
        return {
            "sessions": {k: list(v) for k, v in self.sessions.items()},
            "next_sid": self._next_sid,
            "max_fired_watermark": self.max_fired_watermark,
        }

    # ------------------------------------------------- partial failover

    def _forget_multi_key(self, key: int) -> None:
        """Remove a key's multi-interval entry (native plane also
        un-mirrors its membership set)."""
        self._multi.pop(key, None)

    def drop_key_groups(self, groups, max_parallelism: int = 128) -> int:
        """Remove every session whose key falls in ``groups`` — a lost
        shard's metadata dies with its device state. Fire candidates of
        the dropped sessions become stale and are skipped by pop
        validation (the same lazy discipline merged/extended sessions
        already rely on). Returns sessions dropped."""
        from flink_tpu.state.keygroups import assign_key_groups

        gset = np.asarray(sorted(groups), dtype=np.int64)
        dropped = 0
        used = self._idx.used_slots()
        if len(used):
            keys = np.asarray(self._idx.slot_key[used], dtype=np.int64)
            hit = np.isin(
                assign_key_groups(keys, max_parallelism), gset)
            if hit.any():
                self._idx.free_slots(used[hit].astype(np.int32),
                                     keys=keys[hit], nss=keys[hit])
                dropped += int(hit.sum())
        if self._multi:
            mkeys = np.asarray(list(self._multi), dtype=np.int64)
            mhit = np.isin(
                assign_key_groups(mkeys, max_parallelism), gset)
            for k in mkeys[mhit].tolist():
                dropped += len(self._multi[int(k)])
                self._forget_multi_key(int(k))
        return dropped

    def merge_restore(self, snap: Dict[str, object], key_group_filter,
                      max_parallelism: int = 128) -> int:
        """Partial-failover merge: fold a checkpoint's sessions for the
        given key groups into the LIVE set (survivors untouched — their
        keys never fall in the restored groups). Scalars merge by the
        rules replay depends on: ``next_sid`` takes the max (sids stay
        globally unique), ``max_fired_watermark`` rolls back to the
        checkpoint's so the replayed range's records are not judged
        stale — it re-advances monotonically as replay feeds the
        original watermark sequence. Returns sessions restored."""
        from flink_tpu.state.keygroups import assign_key_groups

        sessions = snap.get("sessions", {})
        restored = 0
        if sessions:
            keys = np.asarray([int(k) for k in sessions],
                              dtype=np.int64)
            keep = np.isin(
                assign_key_groups(keys, max_parallelism),
                np.asarray(sorted(key_group_filter), dtype=np.int64))
            for k, ok in zip(sessions, keep):
                if not ok:
                    continue
                kept = [tuple(iv) for iv in sessions[k]]
                self._store_intervals(int(k), kept)
                restored += len(kept)
                for start, end, sid in kept:
                    self._push_fire(int(end), int(k), int(sid))
        self._drain_fire_buf()
        self._next_sid = max(self._next_sid,
                             int(snap.get("next_sid", 1)))
        self.max_fired_watermark = min(
            self.max_fired_watermark,
            snap.get("max_fired_watermark", _NEG_INF))
        return restored

    @staticmethod
    def filter_snapshot(snap: Dict[str, object], groups,
                        max_parallelism: int = 128) -> Dict[str, object]:
        """A metadata snapshot restricted to ``groups`` (the shard-unit
        split of shard-granular checkpoints); the scalar fields ride
        along whole — each unit is independently restorable."""
        from flink_tpu.state.keygroups import assign_key_groups

        sessions = snap.get("sessions", {})
        if sessions:
            keys = np.asarray([int(k) for k in sessions], dtype=np.int64)
            kg = assign_key_groups(keys, max_parallelism)
            keep = np.isin(kg, np.asarray(sorted(groups), dtype=np.int64))
            sessions = {int(k): list(sessions[k])
                        for k, ok in zip(sessions, keep) if ok}
        return {
            "sessions": sessions,
            "next_sid": snap.get("next_sid", 1),
            "max_fired_watermark": snap.get("max_fired_watermark",
                                            _NEG_INF),
        }

    def restore(self, snap: Dict[str, object],
                key_group_filter=None, max_parallelism: int = 128) -> None:
        self._reset_store()
        self._fire_chunks = []
        self._fire_buf = ([], [], [])
        self._min_pending_end = 1 << 62
        sk, ss, se, ssid = [], [], [], []
        for k, ivs in snap.get("sessions", {}).items():
            kept = [tuple(iv) for iv in ivs]
            if key_group_filter is not None:
                from flink_tpu.state.keygroups import assign_key_groups

                g = int(assign_key_groups(np.array([k]),
                                          max_parallelism)[0])
                if g not in key_group_filter:
                    continue
            if len(kept) == 1:
                s, e, sid = kept[0]
                sk.append(int(k))
                ss.append(int(s))
                se.append(int(e))
                ssid.append(int(sid))
            else:
                self._multi[int(k)] = sorted(kept)
                for start, end, sid in kept:
                    self._push_fire(end, int(k), sid)
        if sk:
            keys = np.asarray(sk, dtype=np.int64)
            slots = self._idx.lookup_or_insert(keys, keys)
            self._s_start[slots] = ss
            self._s_end[slots] = se
            self._s_sid[slots] = ssid
            self._push_fires(np.asarray(se, dtype=np.int64), keys,
                             np.asarray(ssid, dtype=np.int64))
        self._next_sid = snap.get("next_sid", 1)
        self.max_fired_watermark = snap.get("max_fired_watermark", _NEG_INF)


def make_session_meta(gap: int,
                      allowed_lateness: int = 0) -> SessionIntervalSet:
    """The native metadata plane when the C++ library is available, else
    the pure-Python plane — selected per engine exactly the way
    ``make_slot_index`` picks the state-plane index. Fires and snapshots
    are bit-identical across planes (test-pinned).

    ``FLINK_TPU_NATIVE_SESSIONS=0`` forces the Python plane while the
    native state-plane index stays on — the A/B knob bench and parity
    tooling use (the blanket ``FLINK_TPU_NO_NATIVE=1`` disables both).

    Graceful degradation: when the native plane was NOT explicitly
    disabled but is unavailable (the ``.so`` failed to build — missing
    toolchain, compile error) or fails to initialize, the fall back to
    the bit-identical Python plane is LOUD: one warning per distinct
    reason plus the ``flink_tpu.native.native_fallbacks()`` counter —
    a silent fallback would hide a 1.3x throughput regression behind a
    green suite."""
    import os

    from flink_tpu.native import (
        native_disabled,
        note_fallback,
        sessions_available,
    )

    if (os.environ.get("FLINK_TPU_NATIVE_SESSIONS") != "0"
            and not native_disabled()):
        if sessions_available():
            try:
                from flink_tpu.windowing.session_native import (
                    NativeSessionIntervalSet,
                )

                return NativeSessionIntervalSet(gap, allowed_lateness)
            except Exception as e:  # noqa: BLE001 — degrade, loudly
                note_fallback(
                    "native session plane failed to initialize: "
                    f"{type(e).__name__}: {e}")
        else:
            note_fallback(
                "native sessions library unavailable (build failed or "
                "no toolchain) — using the bit-identical Python plane")
    return SessionIntervalSet(gap, allowed_lateness)
