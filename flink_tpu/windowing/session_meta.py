"""Host-side session interval metadata, shared by the single-device and
mesh-sharded session engines.

reference: MergingWindowSet + WindowOperator.java:159-162 — merge *metadata*
(tiny per-key interval lists) lives apart from merged *state* (accumulator
slots). This module is the metadata half; a device engine supplies the state
half (slot resolution + merge/scatter/fire kernels).

Key property exploited by the mesh engine: sessions are per-key and keys are
owned by exactly one shard (key-group routing), so session merging NEVER
crosses shards — the metadata is engine-global, only slot residency is
sharded.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

_NEG_INF = -(1 << 62)


@dataclasses.dataclass
class MergeGroup:
    """A chain-free batch of accumulator merges: within one group no sid is
    both a source and a destination, so a single gather/scatter kernel is
    safe. Groups must execute in order."""

    keys_dst: List[int] = dataclasses.field(default_factory=list)
    sids_dst: List[int] = dataclasses.field(default_factory=list)
    keys_src: List[int] = dataclasses.field(default_factory=list)
    sids_src: List[int] = dataclasses.field(default_factory=list)
    absorbed_sids: List[int] = dataclasses.field(default_factory=list)

    def __len__(self) -> int:
        return len(self.sids_dst)


class SessionIntervalSet:
    """Per-key sorted interval lists + lazy fire heap + sid allocator."""

    def __init__(self, gap: int, allowed_lateness: int = 0):
        self.gap = int(gap)
        self.allowed_lateness = int(allowed_lateness)
        # key -> list of (start, end, sid), sorted by start; usually length 1
        self.sessions: Dict[int, List[Tuple[int, int, int]]] = {}
        self._next_sid = 1
        #: fire candidates as COLUMNAR chunks [(ends, keys, sids), ...] —
        #: the heap's role, but pushes are array appends and the
        #: watermark cut is one vectorized mask (the 10M-key clickstream
        #: creates ~one session per record; per-session heappush/heappop
        #: dominated that profile)
        self._fire_chunks: List[Tuple[np.ndarray, np.ndarray,
                                      np.ndarray]] = []
        #: scalar push buffer (slow-path merges), drained into a chunk
        self._fire_buf: List[Tuple[int, int, int]] = []
        #: earliest pending candidate end — pop_fired returns O(1) when
        #: the watermark has not reached it (the heap's cheap peek)
        self._min_pending_end = 1 << 62
        self.max_fired_watermark = _NEG_INF
        self.late_records_dropped = 0
        # merge-group accumulation during absorb_batch
        self._groups: List[MergeGroup] = []
        self._cur: Optional[MergeGroup] = None
        self._cur_dst: set = set()
        self._cur_src: set = set()

    # ------------------------------------------------------- fire pending

    def _push_fire(self, end: int, key: int, sid: int) -> None:
        self._fire_buf.append((end, key, sid))
        if end < self._min_pending_end:
            self._min_pending_end = end

    def _push_fires(self, ends: np.ndarray, keys: np.ndarray,
                    sids: np.ndarray) -> None:
        if len(ends):
            self._fire_chunks.append((
                np.asarray(ends, dtype=np.int64),
                np.asarray(keys, dtype=np.int64),
                np.asarray(sids, dtype=np.int64)))
            lo = int(ends.min())
            if lo < self._min_pending_end:
                self._min_pending_end = lo

    def _pending_arrays(self):
        if self._fire_buf:
            buf = np.asarray(self._fire_buf, dtype=np.int64)
            self._fire_chunks.append((buf[:, 0], buf[:, 1], buf[:, 2]))
            self._fire_buf = []
        if not self._fire_chunks:
            e = np.empty(0, dtype=np.int64)
            return e, e.copy(), e.copy()
        if len(self._fire_chunks) > 1:
            ends = np.concatenate([c[0] for c in self._fire_chunks])
            keys = np.concatenate([c[1] for c in self._fire_chunks])
            sids = np.concatenate([c[2] for c in self._fire_chunks])
            self._fire_chunks = [(ends, keys, sids)]
        return self._fire_chunks[0]

    # ---------------------------------------------------------------- absorb

    def absorb_batch(self, keys: np.ndarray, ts: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                np.ndarray, List[MergeGroup]]:
        """Sessionize a batch and merge it into the interval set.

        Returns ``(sess_key, sess_sid, rec_to_sess, order, merge_groups)``:
        per batch-local session its key and merged sid (-1 = stale on
        arrival, see below), the sorted-order record->session indirection,
        the lexsort order itself, and the accumulator merges the metadata
        merge implied. Records of a stale session must be dropped (counted
        in ``late_records_dropped`` by the caller via the -1 marker).

        Lateness is decided per *merged session*, not per record — an
        out-of-order record that merges into a live session is never late
        (reference: WindowOperator merges first, then isWindowLate).
        """
        n = len(keys)
        # vectorized batch-local sessionization: sort by (key, ts); a new
        # local session starts at a key change or a gap exceedance
        order = np.lexsort((ts, keys))
        ks, tss = keys[order], ts[order]
        new_sess = np.empty(n, dtype=bool)
        new_sess[0] = True
        new_sess[1:] = (ks[1:] != ks[:-1]) | (tss[1:] - tss[:-1] > self.gap)
        rec_to_sess = np.cumsum(new_sess) - 1
        starts_pos = np.nonzero(new_sess)[0]
        m = len(starts_pos)
        ends_pos = np.empty(m, dtype=np.int64)
        ends_pos[:-1] = starts_pos[1:] - 1
        ends_pos[-1] = n - 1
        sess_key = ks[starts_pos]
        sess_min = tss[starts_pos]
        sess_max = tss[ends_pos]

        self._groups, self._cur = [], None
        self._cur_dst, self._cur_src = set(), set()
        sess_sid = np.empty(m, dtype=np.int64)

        # FAST PATH (the 10M-key clickstream shape): a key with exactly
        # one local session and no stored intervals registers in bulk —
        # sid allocation, interval store, and fire-candidate push all
        # vectorized; only overlapping/merging sessions take the
        # per-session path below
        first_of_key = np.empty(m, dtype=bool)
        first_of_key[0] = True
        first_of_key[1:] = sess_key[1:] != sess_key[:-1]
        only_of_key = first_of_key.copy()
        only_of_key[:-1] &= first_of_key[1:]
        sessions = self.sessions
        exists = np.fromiter((k in sessions for k in sess_key.tolist()),
                             np.bool_, m)
        ends_all = sess_max + self.gap
        if self.max_fired_watermark > _NEG_INF // 2:
            stale = (ends_all - 1 + self.allowed_lateness
                     <= self.max_fired_watermark)
        else:
            stale = np.zeros(m, dtype=bool)
        fast = only_of_key & ~exists
        fresh_stale = fast & stale
        fast &= ~stale
        cnt = int(fast.sum())
        if cnt:
            sids_fast = np.arange(self._next_sid, self._next_sid + cnt,
                                  dtype=np.int64)
            self._next_sid += cnt
            sess_sid[fast] = sids_fast
            fk = sess_key[fast].tolist()
            fs = sess_min[fast].tolist()
            fe = ends_all[fast].tolist()
            for k, s, e, sid in zip(fk, fs, fe, sids_fast.tolist()):
                sessions[k] = [(s, e, sid)]
            self._push_fires(ends_all[fast], sess_key[fast], sids_fast)
        sess_sid[fresh_stale] = -1  # stale on arrival (never stored)
        slow = np.nonzero(~fast & ~fresh_stale)[0]
        for j in slow:
            sess_sid[j] = self._merge_session(
                int(sess_key[j]), int(sess_min[j]), int(ends_all[j]))
        groups = self._groups
        if self._cur is not None and len(self._cur):
            groups.append(self._cur)
        self._groups, self._cur = [], None
        return sess_key, sess_sid, rec_to_sess, order, groups

    def _add_merge(self, key: int, dst_sid: int, src_sid: int) -> None:
        """Queue an accumulator merge. A chain (src was an earlier dst, or
        dst was an earlier src) would make a single gather/scatter kernel
        read stale values, so it closes the current group."""
        if self._cur is None:
            self._cur = MergeGroup()
        elif (src_sid in self._cur_dst or src_sid in self._cur_src
                or dst_sid in self._cur_src):
            self._groups.append(self._cur)
            self._cur = MergeGroup()
            self._cur_dst, self._cur_src = set(), set()
        g = self._cur
        g.keys_dst.append(key)
        g.sids_dst.append(dst_sid)
        g.keys_src.append(key)
        g.sids_src.append(src_sid)
        g.absorbed_sids.append(src_sid)
        self._cur_dst.add(dst_sid)
        self._cur_src.add(src_sid)

    def _merge_session(self, key: int, start: int, end: int) -> int:
        """Merge [start, end) into key's intervals; returns the session id,
        or -1 if the session is stale on arrival. Mirrors
        MergingWindowSet.addWindow: overlapping intervals collapse into
        one; absorbed sessions queue an accumulator merge."""
        intervals = self.sessions.get(key)
        if intervals is None:
            if self._stale(end):
                return -1
            sid = self._alloc_sid()
            self.sessions[key] = [(start, end, sid)]
            self._push_fire(end, key, sid)
            return sid

        overlapping = [iv for iv in intervals
                       if iv[0] <= end and start <= iv[1]]
        if not overlapping:
            if self._stale(end):
                return -1
            sid = self._alloc_sid()
            intervals.append((start, end, sid))
            intervals.sort()
            self._push_fire(end, key, sid)
            return sid

        # absorb into the first overlapping interval's session
        keep = overlapping[0]
        new_start = min(start, keep[0])
        new_end = max(end, keep[1])
        for iv in overlapping[1:]:
            new_start = min(new_start, iv[0])
            new_end = max(new_end, iv[1])
            self._add_merge(key, keep[2], iv[2])
        remaining = [iv for iv in intervals if iv not in overlapping]
        merged = (new_start, new_end, keep[2])
        remaining.append(merged)
        remaining.sort()
        self.sessions[key] = remaining
        if new_end != keep[1]:
            self._push_fire(new_end, key, keep[2])
        return keep[2]

    def _stale(self, end: int) -> bool:
        return (self.max_fired_watermark > _NEG_INF // 2
                and end - 1 + self.allowed_lateness
                <= self.max_fired_watermark)

    def _alloc_sid(self) -> int:
        sid = self._next_sid
        self._next_sid += 1
        return sid

    # ------------------------------------------------------------------ fire

    def pop_fired(self, watermark: int
                  ) -> Tuple[List[int], List[int], List[int], List[int]]:
        """All sessions whose end - 1 <= watermark, removed from the set.
        Returns (keys, starts, ends, sids) in end order. Stale candidates
        (merged or extended sessions) are skipped lazily — one vectorized
        watermark cut selects the due candidates, per-session validation
        runs only over those."""
        if watermark < self._min_pending_end - 1:
            # nothing can be due yet — O(1), the heap's cheap peek
            self.max_fired_watermark = max(self.max_fired_watermark,
                                           watermark)
            return [], [], [], []
        p_ends, p_keys, p_sids = self._pending_arrays()
        if not len(p_ends):
            self._min_pending_end = 1 << 62
            self.max_fired_watermark = max(self.max_fired_watermark,
                                           watermark)
            return [], [], [], []
        due = p_ends - 1 <= watermark
        if due.any():
            keep = ~due
            d_ends = p_ends[due]
            d_keys = p_keys[due]
            d_sids = p_sids[due]
            self._fire_chunks = (
                [(p_ends[keep], p_keys[keep], p_sids[keep])]
                if keep.any() else [])
            self._min_pending_end = (int(p_ends[keep].min())
                                     if keep.any() else 1 << 62)
            order = np.argsort(d_ends, kind="stable")  # heap pop order
            d_ends, d_keys, d_sids = (d_ends[order], d_keys[order],
                                      d_sids[order])
        else:
            d_ends = d_keys = d_sids = np.empty(0, dtype=np.int64)
        keys: List[int] = []
        starts: List[int] = []
        ends: List[int] = []
        sids: List[int] = []
        sessions = self.sessions
        for end, key, sid in zip(d_ends.tolist(), d_keys.tolist(),
                                 d_sids.tolist()):
            intervals = sessions.get(key)
            if not intervals:
                continue
            cur = next((iv for iv in intervals if iv[2] == sid), None)
            if cur is None or cur[1] != end:
                continue  # stale entry
            keys.append(key)
            starts.append(cur[0])
            ends.append(end)
            sids.append(sid)
            if len(intervals) == 1:
                del sessions[key]
            else:
                intervals.remove(cur)
        self.max_fired_watermark = max(self.max_fired_watermark, watermark)
        return keys, starts, ends, sids

    # -------------------------------------------------------------- snapshot

    def snapshot(self) -> Dict[str, object]:
        return {
            "sessions": {k: list(v) for k, v in self.sessions.items()},
            "next_sid": self._next_sid,
            "max_fired_watermark": self.max_fired_watermark,
        }

    def restore(self, snap: Dict[str, object],
                key_group_filter=None, max_parallelism: int = 128) -> None:
        self.sessions = {}
        self._fire_chunks = []
        self._fire_buf = []
        self._min_pending_end = 1 << 62
        for k, ivs in snap.get("sessions", {}).items():
            kept = [tuple(iv) for iv in ivs]
            if key_group_filter is not None:
                from flink_tpu.state.keygroups import assign_key_groups

                g = int(assign_key_groups(np.array([k]),
                                          max_parallelism)[0])
                if g not in key_group_filter:
                    continue
            self.sessions[int(k)] = kept
            for start, end, sid in kept:
                self._push_fire(end, int(k), sid)
        self._next_sid = snap.get("next_sid", 1)
        self.max_fired_watermark = snap.get("max_fired_watermark", _NEG_INF)
