"""Session windows: merging windows with device accumulators.

reference semantics: EventTimeSessionWindows + MergingWindowSet
(streaming/runtime/operators/windowing/WindowOperator.java:159-162 splits
merge *metadata* from merged *state*; MergingWindowSet tracks interval merges,
windowMergingState merges namespaces). The TPU re-design keeps exactly that
split:

- **Host**: per-key sorted interval lists ``key -> [(start, end, sid)]``
  (tiny per key), a lazy fire heap, and a session-id allocator.
- **Device**: one accumulator slot per live session. Batch-local
  sessionization is vectorized (lexsort + gap scan); record values scatter
  straight into their final session slot; merging two sessions is a batched
  ``acc.at[dst].op(acc[src])`` scatter (duplicate dst allowed — scatter
  reduces), then the absorbed slots reset to identity.

A session [start, end) fires when watermark >= end - 1 where
end = last_event_ts + gap. Extensions/merges invalidate heap entries lazily
(entries carry their sid+end; stale ones are skipped on pop).
"""

from __future__ import annotations

import heapq
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.ops.segment_ops import SCATTER_METHOD, pad_bucket_size, pad_i32
from flink_tpu.state.slot_table import SlotTable
from flink_tpu.windowing.aggregates import AggregateFunction, _JIT_CACHE
from flink_tpu.windowing.windower import WINDOW_END_FIELD, WINDOW_START_FIELD

_NEG_INF = -(1 << 62)


def _merge_jit(agg: AggregateFunction):
    """acc[dst] op= acc[src] for arrays of (dst, src), then reset src slots."""
    methods = tuple(SCATTER_METHOD[l.reduce] for l in agg.leaves)
    idents = tuple(l.identity for l in agg.leaves)
    key = ("session-merge", methods, idents,
           tuple(l.dtype.str for l in agg.leaves))
    fn = _JIT_CACHE.get(key)
    if fn is None:

        @partial(jax.jit, donate_argnums=(0,))
        def merge(accs, dst, src):
            out = []
            for a, m, i in zip(accs, methods, idents):
                moved = a[src]
                a = getattr(a.at[dst], m)(moved)
                # src != dst for real pairs; padded lanes have src == dst == 0
                a = a.at[src].set(i)
                out.append(a)
            return tuple(out)

        _JIT_CACHE[key] = fn = merge
    return fn


class SessionWindower:
    """Keyed session windows over one shard (single device)."""

    def __init__(
        self,
        gap: int,
        agg: AggregateFunction,
        capacity: int = 1 << 16,
        max_parallelism: int = 128,
        allowed_lateness: int = 0,
        spill: dict = None,
    ) -> None:
        self.gap = int(gap)
        self.agg = agg
        # Late records within the allowance start a NEW session (emitted as an
        # additional partial result) since fired sessions are freed eagerly;
        # records beyond the allowance are dropped.
        self.allowed_lateness = int(allowed_lateness)
        self.table = SlotTable(agg, capacity=capacity,
                               max_parallelism=max_parallelism,
                               **(spill or {}))
        # key -> list of (start, end, sid), sorted by start; usually length 1
        self.sessions: Dict[int, List[Tuple[int, int, int]]] = {}
        self._next_sid = 1
        self._fire_heap: List[Tuple[int, int, int]] = []  # (end, key, sid)
        self.max_fired_watermark = _NEG_INF
        self.late_records_dropped = 0
        # pending accumulator merges (dst, src) + absorbed session ids whose
        # host slots must stay allocated until the merge kernel has run
        self._merge_dst: List[int] = []
        self._merge_src: List[int] = []
        self._merge_dst_set: set = set()
        self._merge_src_set: set = set()
        self._absorbed_sids: List[int] = []

    # ---------------------------------------------------------------- ingest

    def process_batch(self, batch: RecordBatch) -> None:
        n = len(batch)
        if n == 0:
            return
        ts = np.asarray(batch.timestamps, dtype=np.int64)
        keys = np.asarray(batch.key_ids, dtype=np.int64)

        # NOTE: lateness is decided per *merged session*, not per record —
        # an out-of-order record that merges into a live session is never
        # late (reference: WindowOperator merges first, then isWindowLate).
        # _merge_session returns sid -1 for sessions that are stale on
        # arrival; their records route to the identity slot 0.

        # vectorized batch-local sessionization: sort by (key, ts); a new
        # local session starts at a key change or a gap exceedance
        order = np.lexsort((ts, keys))
        ks, tss = keys[order], ts[order]
        new_sess = np.empty(n, dtype=bool)
        new_sess[0] = True
        new_sess[1:] = (ks[1:] != ks[:-1]) | (tss[1:] - tss[:-1] > self.gap)
        sess_of_sorted = np.cumsum(new_sess) - 1
        starts_pos = np.nonzero(new_sess)[0]
        m = len(starts_pos)
        ends_pos = np.empty(m, dtype=np.int64)
        ends_pos[:-1] = starts_pos[1:] - 1
        ends_pos[-1] = n - 1
        sess_key = ks[starts_pos]
        sess_min = tss[starts_pos]
        sess_max = tss[ends_pos]

        # merge each batch-local session into the persistent interval set
        # (pure metadata — slot lookups are batched below)
        sess_sid = np.empty(m, dtype=np.int64)
        for j in range(m):
            sess_sid[j] = self._merge_session(
                int(sess_key[j]), int(sess_min[j]),
                int(sess_max[j]) + self.gap)

        live_sess = sess_sid >= 0
        if not live_sess.all():
            # stale-on-arrival sessions: route their records to slot 0
            sess_counts = np.diff(np.append(starts_pos, n))
            self.late_records_dropped += int(
                sess_counts[~live_sess].sum())
        # ONE vectorized lookup for all session slots, then scatter records
        slot_of_sess = np.zeros(m, dtype=np.int32)
        if live_sess.any():
            slot_of_sess[live_sess] = self.table.lookup_or_insert(
                sess_key[live_sess], sess_sid[live_sess])
        rec_slots = np.empty(n, dtype=np.int32)
        rec_slots[order] = slot_of_sess[sess_of_sorted]
        self.table.scatter(rec_slots, self.agg.map_input(batch))
        self._flush_merges()

    def _add_merge(self, key: int, dst_sid: int, src_sid: int) -> None:
        """Queue an accumulator merge by session id. A chain (src was an
        earlier dst, or dst was an earlier src) would make the single
        gather/scatter kernel read stale values, so flush the pending batch
        first."""
        if (src_sid in self._merge_dst_set or src_sid in self._merge_src_set
                or dst_sid in self._merge_src_set):
            self._flush_merges()
        self._merge_dst.append((key, dst_sid))
        self._merge_src.append((key, src_sid))
        self._merge_dst_set.add(dst_sid)
        self._merge_src_set.add(src_sid)

    def _flush_merges(self) -> None:
        if not self._merge_dst:
            return
        dk = np.asarray([p[0] for p in self._merge_dst], dtype=np.int64)
        ds = np.asarray([p[1] for p in self._merge_dst], dtype=np.int64)
        sk = np.asarray([p[0] for p in self._merge_src], dtype=np.int64)
        ss = np.asarray([p[1] for p in self._merge_src], dtype=np.int64)
        # ONE combined lookup: with a spill tier, a second lookup could
        # evict slots the first just resolved — dst and src must be
        # resident simultaneously for the merge kernel
        m = len(dk)
        both = self.table.lookup_or_insert(
            np.concatenate([dk, sk]), np.concatenate([ds, ss]))
        dst_slots, src_slots = both[:m], both[m:]
        size = pad_bucket_size(len(dst_slots))
        self.table.mark_dirty(dst_slots)
        self.table.mark_dirty(src_slots)
        self.table.accs = _merge_jit(self.agg)(
            self.table.accs,
            pad_i32(dst_slots, size, fill=0),
            pad_i32(src_slots, size, fill=0))
        # absorbed host slots are only reusable once their values have moved
        # (free_index_only: the merge kernel already reset the device slots)
        if self._absorbed_sids:
            self.table.free_index_only(self._absorbed_sids)
            self._absorbed_sids = []
        self._merge_dst, self._merge_src = [], []
        self._merge_dst_set, self._merge_src_set = set(), set()

    def _merge_session(self, key: int, start: int, end: int) -> int:
        """Merge [start, end) into key's intervals; returns the session id,
        or -1 if the session is stale on arrival (no live session to merge
        into and its own end is already past the lateness allowance).

        Mirrors MergingWindowSet.addWindow: overlapping intervals collapse
        into one; absorbed sessions queue an accumulator merge (dst, src).
        Pure host metadata — device slot lookups are batched by the caller.
        """
        intervals = self.sessions.get(key)
        if intervals is None:
            if self._stale(end):
                return -1
            sid = self._alloc_sid()
            self.sessions[key] = [(start, end, sid)]
            heapq.heappush(self._fire_heap, (end, key, sid))
            return sid

        overlapping = [iv for iv in intervals
                       if iv[0] <= end and start <= iv[1]]
        if not overlapping:
            if self._stale(end):
                return -1
            sid = self._alloc_sid()
            intervals.append((start, end, sid))
            intervals.sort()
            heapq.heappush(self._fire_heap, (end, key, sid))
            return sid

        # absorb into the first overlapping interval's session
        keep = overlapping[0]
        new_start = min(start, keep[0])
        new_end = max(end, keep[1])
        for iv in overlapping[1:]:
            new_start = min(new_start, iv[0])
            new_end = max(new_end, iv[1])
            self._add_merge(key, keep[2], iv[2])
            self._absorbed_sids.append(iv[2])
        remaining = [iv for iv in intervals if iv not in overlapping]
        merged = (new_start, new_end, keep[2])
        remaining.append(merged)
        remaining.sort()
        self.sessions[key] = remaining
        if new_end != keep[1]:
            heapq.heappush(self._fire_heap, (new_end, key, keep[2]))
        return keep[2]

    def _stale(self, end: int) -> bool:
        """A (merged) session ending at ``end`` is stale iff the watermark
        has already passed end - 1 + lateness."""
        return (self.max_fired_watermark > _NEG_INF // 2
                and end - 1 + self.allowed_lateness <= self.max_fired_watermark)

    def _alloc_sid(self) -> int:
        sid = self._next_sid
        self._next_sid += 1
        return sid

    # ------------------------------------------------------------------ fire

    def on_watermark(self, watermark: int) -> List[RecordBatch]:
        fired_keys: List[int] = []
        fired_starts: List[int] = []
        fired_ends: List[int] = []
        fired_sids: List[int] = []
        while self._fire_heap and self._fire_heap[0][0] - 1 <= watermark:
            end, key, sid = heapq.heappop(self._fire_heap)
            intervals = self.sessions.get(key)
            if not intervals:
                continue
            cur = next((iv for iv in intervals if iv[2] == sid), None)
            if cur is None or cur[1] != end:
                continue  # stale entry (merged or extended)
            fired_keys.append(key)
            fired_starts.append(cur[0])
            fired_ends.append(end)
            fired_sids.append(sid)
            intervals.remove(cur)
            if not intervals:
                del self.sessions[key]
        self.max_fired_watermark = max(self.max_fired_watermark, watermark)
        if not fired_keys:
            return []
        total = len(fired_keys)
        # with a bounded device table, a mass fire (e.g. end of stream) can
        # exceed what fits resident at once — fire in budget-sized chunks,
        # freeing each chunk's sessions before resolving the next
        chunk = total
        if self.table.max_device_slots:
            chunk = max(self.table.max_device_slots // 2, 1024)
        out: List[RecordBatch] = []
        for a in range(0, total, chunk):
            b = min(a + chunk, total)
            fired_slots = self.table.lookup_or_insert(
                np.asarray(fired_keys[a:b], dtype=np.int64),
                np.asarray(fired_sids[a:b], dtype=np.int64))
            matrix = np.asarray(fired_slots, dtype=np.int32)[:, None]
            results = self.table.fire(matrix)
            self.table.free_namespaces(fired_sids[a:b])
            m = b - a
            cols = {
                KEY_ID_FIELD: np.asarray(fired_keys[a:b], dtype=np.int64),
                WINDOW_START_FIELD: np.asarray(fired_starts[a:b],
                                               dtype=np.int64),
                WINDOW_END_FIELD: np.asarray(fired_ends[a:b],
                                             dtype=np.int64),
                TIMESTAMP_FIELD: np.asarray(fired_ends[a:b],
                                            dtype=np.int64) - 1,
            }
            cols.update(results)
            out.append(RecordBatch(cols))
        return out

    # -------------------------------------------------------------- snapshot

    def snapshot(self, mode: str = "full") -> Dict[str, object]:
        self._flush_merges()  # pending accumulator moves must be material
        if mode == "delta":
            table = self.table.snapshot_delta()
        else:
            table = self.table.snapshot(reset_dirty=(mode != "savepoint"))
        return {
            "table": table,
            "sessions": {k: list(v) for k, v in self.sessions.items()},
            "next_sid": self._next_sid,
            "max_fired_watermark": self.max_fired_watermark,
        }

    def restore(self, snap: Dict[str, object], key_group_filter=None) -> None:
        if "table" in snap:
            self.table.restore(snap["table"], key_group_filter=key_group_filter)
        self.sessions = {}
        self._fire_heap = []
        for k, ivs in snap.get("sessions", {}).items():
            kept = [tuple(iv) for iv in ivs]
            if key_group_filter is not None:
                from flink_tpu.state.keygroups import assign_key_groups

                g = int(assign_key_groups(np.array([k]),
                                          self.table.max_parallelism)[0])
                if g not in key_group_filter:
                    continue
            self.sessions[int(k)] = kept
            for start, end, sid in kept:
                heapq.heappush(self._fire_heap, (end, int(k), sid))
        self._next_sid = snap.get("next_sid", 1)
        self.max_fired_watermark = snap.get("max_fired_watermark", _NEG_INF)
