"""Session windows: merging windows with device accumulators.

reference semantics: EventTimeSessionWindows + MergingWindowSet
(streaming/runtime/operators/windowing/WindowOperator.java:159-162 splits
merge *metadata* from merged *state*; MergingWindowSet tracks interval merges,
windowMergingState merges namespaces). The TPU re-design keeps exactly that
split:

- **Host**: per-key sorted interval lists ``key -> [(start, end, sid)]``
  (tiny per key), a lazy fire heap, and a session-id allocator — factored
  into :class:`flink_tpu.windowing.session_meta.SessionIntervalSet`, shared
  with the mesh-sharded engine.
- **Device**: one accumulator slot per live session. Batch-local
  sessionization is vectorized (lexsort + gap scan); record values scatter
  straight into their final session slot; merging two sessions is a batched
  ``acc.at[dst].op(acc[src])`` scatter (duplicate dst allowed — scatter
  reduces), then the absorbed slots reset to identity.

A session [start, end) fires when watermark >= end - 1 where
end = last_event_ts + gap. Extensions/merges invalidate heap entries lazily
(entries carry their sid+end; stale ones are skipped on pop).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.ops.segment_ops import pad_bucket_size, pad_i32
from flink_tpu.state.slot_table import SlotTable
from flink_tpu.stateplane import flat_merge_pairs
from flink_tpu.windowing.aggregates import AggregateFunction
from flink_tpu.windowing.session_meta import MergeGroup, make_session_meta
from flink_tpu.windowing.windower import WINDOW_END_FIELD, WINDOW_START_FIELD


def _merge_jit(agg: AggregateFunction):
    """acc[dst] op= acc[src] for arrays of (dst, src), then reset src slots."""
    return flat_merge_pairs(agg.leaves)


class SessionWindower:
    """Keyed session windows over one shard (single device)."""

    def __init__(
        self,
        gap: int,
        agg: AggregateFunction,
        capacity: int = 1 << 16,
        max_parallelism: int = 128,
        allowed_lateness: int = 0,
        spill: dict = None,
    ) -> None:
        self.gap = int(gap)
        self.agg = agg
        # Late records within the allowance start a NEW session (emitted as an
        # additional partial result) since fired sessions are freed eagerly;
        # records beyond the allowance are dropped.
        self.allowed_lateness = int(allowed_lateness)
        spill_kwargs = dict(spill or {})
        if spill_kwargs.get("max_device_slots"):
            # sessions are one row per namespace (sid) — the paged spill
            # layout moves eviction cohorts instead of per-session
            # entries (reference: RocksDB block granularity;
            # slot_table.py spill_layout="pages")
            spill_kwargs.setdefault("spill_layout", "pages")
        if spill_kwargs.get("spill_layout", "pages") == "pages":
            # this windower frees by SLOT (free_rows /
            # free_index_only_slots) — skip the per-namespace registry,
            # which costs O(sessions) Python per batch at one row per
            # sid. An explicit spill_layout="namespaces" keeps the
            # registry: its eviction path walks it.
            spill_kwargs.setdefault("track_namespaces", False)
        self.table = SlotTable(agg, capacity=capacity,
                               max_parallelism=max_parallelism,
                               **spill_kwargs)
        #: session-interval metadata: the native C sweep when compiled,
        #: else the pure-Python plane (bit-identical fires/snapshots)
        self.meta = make_session_meta(self.gap, self.allowed_lateness)

    @property
    def late_records_dropped(self) -> int:
        return self.meta.late_records_dropped

    @property
    def max_fired_watermark(self) -> int:
        return self.meta.max_fired_watermark

    @property
    def sessions(self):
        return self.meta.sessions

    def spill_counters(self):
        """Paged spill traffic (pages/rows evicted+reloaded, rows split
        on reload); zeros when the table is unbounded."""
        return self.table.spill_counters()

    # ---------------------------------------------------------- point query

    def query_sessions_batch(self, key_ids):
        """Batched point lookup: {session_end -> result columns} per
        requested key. The keys' live sessions come from host metadata;
        their accumulators are read through ONE gather kernel + ONE
        device read for the whole batch (SlotTable.query_batch_pairs) —
        spilled sessions answer from the page tier, read-only."""
        key_ids = np.asarray(key_ids, dtype=np.int64)
        n = len(key_ids)
        results = [dict() for _ in range(n)]
        rows: List[Tuple[int, int, int]] = []  # (request row, sid, end)
        for r in range(n):
            for _start, end, sid in self.meta.sessions.get(
                    int(key_ids[r]), []):
                rows.append((r, int(sid), int(end)))
        if not rows:
            return results
        rr = np.asarray([t[0] for t in rows], dtype=np.int64)
        sids = np.asarray([t[1] for t in rows], dtype=np.int64)
        found, leaves = self.table.query_batch_pairs(key_ids[rr], sids)
        finished = self.agg.finish(tuple(leaves))
        cols = {name: np.asarray(col) for name, col in finished.items()}
        for j, (r, _sid, end) in enumerate(rows):
            if found[j]:
                results[r][end] = {name: col[j].item()
                                   for name, col in cols.items()}
        return results

    def query_sessions(self, key_id: int):
        """Single-key form — a batch of one (same contract as
        MeshSessionEngine.query_sessions)."""
        return self.query_sessions_batch(
            np.asarray([key_id], dtype=np.int64))[0]

    # ---------------------------------------------------------------- ingest

    def process_batch(self, batch: RecordBatch) -> None:
        n = len(batch)
        if n == 0:
            return
        ts = np.asarray(batch.timestamps, dtype=np.int64)
        keys = np.asarray(batch.key_ids, dtype=np.int64)

        res = self.meta.absorb_batch_ex(keys, ts, want_fresh=False)
        sess_key, sess_sid = res.sess_key, res.sess_sid
        rec_to_sess, order = res.rec_to_sess, res.order
        for g in res.groups:
            self._run_merge_group(g)

        live_sess = sess_sid >= 0
        if not live_sess.all():
            # stale-on-arrival sessions: route their records to slot 0
            starts_pos = np.nonzero(
                np.diff(rec_to_sess, prepend=-1) > 0)[0]
            sess_counts = np.diff(np.append(starts_pos, n))
            self.meta.late_records_dropped += int(
                sess_counts[~live_sess].sum())
        # ONE vectorized lookup for all session slots, then scatter
        # records; the native metadata plane's folded slots skip the
        # state-table hash probe for sessions whose fold is still valid
        m = len(sess_key)
        slot_of_sess = np.zeros(m, dtype=np.int32)
        if live_sess.any():
            slot_of_sess[live_sess] = self.table.lookup_or_insert(
                sess_key[live_sess], sess_sid[live_sess],
                hints=(None if res.slot_hint is None
                       else res.slot_hint[live_sess]))
            self.meta.note_slots(sess_key[live_sess],
                                 sess_sid[live_sess],
                                 slot_of_sess[live_sess],
                                 rows=(None if res.meta_row is None
                                       else res.meta_row[live_sess]))
        rec_slots = np.empty(n, dtype=np.int32)
        rec_slots[order] = slot_of_sess[rec_to_sess]
        self.table.scatter(rec_slots, self.agg.map_input(batch))

    def _run_merge_group(self, g: MergeGroup) -> None:
        """Resolve a chain-free merge group's slots and move accumulators
        in one kernel, then free the absorbed host slots (their device
        slots were reset by the kernel)."""
        dk = np.asarray(g.keys_dst, dtype=np.int64)
        ds = np.asarray(g.sids_dst, dtype=np.int64)
        sk = np.asarray(g.keys_src, dtype=np.int64)
        ss = np.asarray(g.sids_src, dtype=np.int64)
        # ONE combined lookup: with a spill tier, a second lookup could
        # evict slots the first just resolved — dst and src must be
        # resident simultaneously for the merge kernel
        m = len(dk)
        both = self.table.lookup_or_insert(
            np.concatenate([dk, sk]), np.concatenate([ds, ss]))
        dst_slots, src_slots = both[:m], both[m:]
        size = pad_bucket_size(len(dst_slots))
        self.table.mark_dirty(dst_slots)
        self.table.mark_dirty(src_slots)
        self.table.accs = _merge_jit(self.agg)(
            self.table.accs,
            pad_i32(dst_slots, size, fill=0),
            pad_i32(src_slots, size, fill=0))
        # absorbed host slots are only reusable once their values have
        # moved (the merge kernel already reset the device slots); the
        # slots are in hand, so the free needs no registry walk
        self.table.free_index_only_slots(src_slots, g.absorbed_sids)

    # ------------------------------------------------------------------ fire

    #: fires may be dispatched async (see on_watermark(async_ok=True))
    supports_async_fires = True

    def on_watermark(self, watermark: int,
                     async_ok: bool = False) -> List[RecordBatch]:
        pop = self.meta.pop_fired_ex(watermark)
        fired_keys, fired_starts = pop.keys, pop.starts
        fired_ends, fired_sids = pop.ends, pop.sids
        if not len(fired_keys):
            return []
        total = len(fired_keys)
        # with a bounded device table, a mass fire (e.g. end of stream) can
        # exceed what fits resident at once — fire in budget-sized chunks,
        # freeing each chunk's sessions before resolving the next
        chunk = total
        if self.table.max_device_slots:
            chunk = max(self.table.max_device_slots // 2, 1024)
        out: List[RecordBatch] = []
        for a in range(0, total, chunk):
            b = min(a + chunk, total)
            fired_slots = self.table.lookup_or_insert(
                np.asarray(fired_keys[a:b], dtype=np.int64),
                np.asarray(fired_sids[a:b], dtype=np.int64),
                hints=(None if pop.slot_hint is None
                       else pop.slot_hint[a:b]))
            matrix = np.asarray(fired_slots, dtype=np.int32)[:, None]
            cols = {
                KEY_ID_FIELD: np.asarray(fired_keys[a:b], dtype=np.int64),
                WINDOW_START_FIELD: np.asarray(fired_starts[a:b],
                                               dtype=np.int64),
                WINDOW_END_FIELD: np.asarray(fired_ends[a:b],
                                             dtype=np.int64),
                TIMESTAMP_FIELD: np.asarray(fired_ends[a:b],
                                            dtype=np.int64) - 1,
            }
            if async_ok:
                # dispatch the fire and free the sessions immediately —
                # the reset is device-queue-ordered BEHIND the fire
                # kernel, so the deferred host read never races it
                pending = self.table.fire_async(matrix, None)
                self.table.free_rows(fired_slots, fired_sids[a:b])
                if pending is None:
                    continue
                inner = pending.build

                def build(host, inner=inner, cols=cols):
                    _, results = inner(host)
                    full = dict(cols)
                    full.update(results)
                    return RecordBatch(full)

                pending.build = build
                out.append(pending)
                continue
            results = self.table.fire(matrix)
            self.table.free_rows(fired_slots, fired_sids[a:b])
            cols.update(results)
            out.append(RecordBatch(cols))
        return out

    # -------------------------------------------------------------- snapshot

    def snapshot(self, mode: str = "full") -> Dict[str, object]:
        if mode == "delta":
            table = self.table.snapshot_delta()
        else:
            table = self.table.snapshot(reset_dirty=(mode != "savepoint"))
        return {"table": table, **self.meta.snapshot()}

    def restore(self, snap: Dict[str, object], key_group_filter=None) -> None:
        if "table" in snap:
            self.table.restore(snap["table"], key_group_filter=key_group_filter)
        self.meta.restore(snap, key_group_filter=key_group_filter,
                          max_parallelism=self.table.max_parallelism)
