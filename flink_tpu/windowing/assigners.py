"""Window assigners, re-designed around *slices*.

The reference assigns each record to every window it belongs to and keeps
per-(key, window) state (reference:
streaming/runtime/operators/windowing/WindowOperator.java:293 processElement —
a record in a HOP(1h, 5m) window writes 12 state entries). The table runtime's
slicing optimization instead assigns each record to exactly ONE slice and
merges slices at fire time (reference:
flink-table-runtime/.../window/tvf/slicing/SliceAssigners.java:243
HoppingSliceAssigner.assignSliceEnd; WindowAggOperator.java:216).

Here slicing is the *only* mode for aligned windows — it is strictly better on
TPU because a slice assignment is one vectorized arithmetic op over the
timestamp column, and the fire-time merge is a gather + axis-reduce on device.

All times are int64 milliseconds. A slice/window is identified by its END
timestamp (exclusive end; a window [s, e) fires when watermark >= e - 1).
"""

from __future__ import annotations

import dataclasses
import math
from typing import List

import numpy as np


from flink_tpu.core.annotations import public, public_evolving

@dataclasses.dataclass(frozen=True)
class WindowAssigner:
    """Base: maps timestamps -> slice ends, and window ends -> slice ranges."""

    size: int            # full window span (ms)
    slide: int           # distance between consecutive window ends (ms)
    slice_width: int     # width of one slice (ms)
    offset: int = 0
    #: True for wall-clock (arrival-time) assigners — fires are driven by
    #: processing-time ticks instead of watermarks
    is_processing_time = False

    @property
    def slices_per_window(self) -> int:
        return self.size // self.slice_width

    @property
    def is_merging(self) -> bool:
        return False

    def assign_slice_ends(self, timestamps: np.ndarray) -> np.ndarray:
        """Each record -> exclusive end of its slice. Vectorized."""
        ts = np.asarray(timestamps, dtype=np.int64)
        w = self.slice_width
        start = ts - np.remainder(ts - self.offset, w)
        return start + w

    def slice_plan(self, slice_ends: np.ndarray):
        """(unique_ends, inverse) without sorting the batch.

        ``np.unique(return_inverse=True)`` sorts all n rows (~50 ms per
        1M-row batch) to find what is typically a handful of distinct
        slice ends. Slice ends are multiples of ``slice_width`` in a
        narrow range per batch, so bucketing by ``(se - min) // width``
        gets the same answer in O(n) passes. Falls back to ``np.unique``
        for pathological spreads (wildly out-of-order timestamps)."""
        se = np.asarray(slice_ends, dtype=np.int64)
        base = int(se.min())
        w = self.slice_width
        span = (int(se.max()) - base) // w + 1
        if span > (1 << 16):
            uniq, inv = np.unique(se, return_inverse=True)
            return uniq, inv
        sidx = (se - base) // w
        counts = np.bincount(sidx, minlength=span)
        present = np.nonzero(counts)[0]
        uniq = base + present * w
        if len(present) == span:
            return uniq, sidx
        remap = np.cumsum(counts > 0) - 1
        return uniq, remap[sidx]

    def window_ends_for_slice(self, slice_end: int) -> List[int]:
        """All window ends this slice contributes to (ascending)."""
        first = _align_up(slice_end, self.slide, self.offset)
        last = slice_end + self.size - self.slice_width
        return list(range(first, last + 1, self.slide))

    def slice_ends_for_window(self, window_end: int) -> List[int]:
        """The slices making up window (window_end - size, window_end]."""
        first = window_end - self.size + self.slice_width
        return list(range(first, window_end + 1, self.slice_width))

    def last_window_end_for_slice(self, slice_end: int) -> int:
        """After this window fires (plus lateness), the slice can be freed."""
        return self.window_ends_for_slice(slice_end)[-1]

    def last_window_ends(self, slice_ends: np.ndarray) -> np.ndarray:
        """Vectorized last participating window end per slice (used by the
        late-record filter; must agree exactly with
        ``window_ends_for_slice(se)[-1]``)."""
        se = np.asarray(slice_ends, dtype=np.int64)
        w = se + self.size - self.slice_width
        return w - np.remainder(w - self.offset, self.slide)

    def window_start(self, window_end: int) -> int:
        return window_end - self.size


def _align_up(t: int, step: int, offset: int = 0) -> int:
    """Smallest multiple of ``step`` (+offset) that is >= t."""
    r = (t - offset) % step
    return t if r == 0 else t + (step - r)


@public
class TumblingEventTimeWindows(WindowAssigner):
    """reference: streaming/api/windowing/assigners/TumblingEventTimeWindows.java
    — one slice per window, fire = emit slice."""

    def __init__(self, size_ms: int, offset_ms: int = 0):
        super().__init__(size=size_ms, slide=size_ms, slice_width=size_ms,
                         offset=offset_ms)

    @staticmethod
    def of(size_ms: int, offset_ms: int = 0) -> "TumblingEventTimeWindows":
        return TumblingEventTimeWindows(size_ms, offset_ms)


@public
class SlidingEventTimeWindows(WindowAssigner):
    """reference: streaming/api/windowing/assigners/SlidingEventTimeWindows.java,
    executed with the HOP slice-sharing strategy
    (reference: SliceAssigners.java HoppingSliceAssigner)."""

    def __init__(self, size_ms: int, slide_ms: int, offset_ms: int = 0):
        width = math.gcd(size_ms, slide_ms)
        super().__init__(size=size_ms, slide=slide_ms, slice_width=width,
                         offset=offset_ms)

    @staticmethod
    def of(size_ms: int, slide_ms: int, offset_ms: int = 0) -> "SlidingEventTimeWindows":
        return SlidingEventTimeWindows(size_ms, slide_ms, offset_ms)


@public_evolving
class TumblingProcessingTimeWindows(TumblingEventTimeWindows):
    """Windows over WALL-CLOCK arrival time (reference:
    TumblingProcessingTimeWindows.java + WindowOperator.onProcessingTime:497).
    Records are assigned by the time they reach the operator; fires are
    driven by the executor's processing-time ticks, not watermarks."""

    is_processing_time = True

    @staticmethod
    def of(size_ms: int, offset_ms: int = 0
           ) -> "TumblingProcessingTimeWindows":
        return TumblingProcessingTimeWindows(size_ms, offset_ms)


@public_evolving
class SlidingProcessingTimeWindows(SlidingEventTimeWindows):
    """reference: SlidingProcessingTimeWindows.java — HOP over arrival
    time, slice-shared like the event-time form."""

    is_processing_time = True

    @staticmethod
    def of(size_ms: int, slide_ms: int, offset_ms: int = 0
           ) -> "SlidingProcessingTimeWindows":
        return SlidingProcessingTimeWindows(size_ms, slide_ms, offset_ms)


@public
class CumulativeEventTimeWindows(WindowAssigner):
    """CUMULATE TVF (reference: SliceAssigners.java CumulativeSliceAssigner):
    windows [s, s+step), [s, s+2*step) ... [s, s+max_size)."""

    def __init__(self, max_size_ms: int, step_ms: int, offset_ms: int = 0):
        super().__init__(size=max_size_ms, slide=step_ms, slice_width=step_ms,
                         offset=offset_ms)

    def window_ends_for_slice(self, slice_end: int) -> List[int]:
        # slice contributes to window ends slice_end, +step ... up to the end
        # of its cumulate span.
        span_start = slice_end - ((slice_end - self.offset - self.slice_width)
                                  % self.size)
        span_end = span_start + self.size - self.slice_width
        return list(range(slice_end, span_end + 1, self.slide))

    def slice_ends_for_window(self, window_end: int) -> List[int]:
        span_start_end = window_end - ((window_end - self.offset - self.slice_width)
                                       % self.size)
        return list(range(span_start_end, window_end + 1, self.slice_width))

    def window_start(self, window_end: int) -> int:
        return window_end - ((window_end - self.offset - self.slice_width)
                             % self.size) - self.slice_width

    def last_window_ends(self, slice_ends: np.ndarray) -> np.ndarray:
        se = np.asarray(slice_ends, dtype=np.int64)
        span_start = se - np.remainder(
            se - self.offset - self.slice_width, self.size)
        return span_start + self.size - self.slice_width


@public
@dataclasses.dataclass(frozen=True)
class EventTimeSessionWindows:
    """Session windows with a gap; merging happens on host metadata with
    device accumulators (reference: WindowOperator.java MergingWindowSet /
    streaming/api/windowing/assigners/EventTimeSessionWindows.java)."""

    gap: int

    @staticmethod
    def with_gap(gap_ms: int) -> "EventTimeSessionWindows":
        return EventTimeSessionWindows(gap=gap_ms)

    @property
    def is_merging(self) -> bool:
        return True
