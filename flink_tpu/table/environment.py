"""StreamTableEnvironment — SQL entry point + result materialization.

reference: flink-table/flink-table-api-java/.../internal/TableEnvironmentImpl.java
(:936 executeSql), StreamTableEnvironmentImpl (fromDataStream/toDataStream).
Catalog here is a flat in-memory name -> Table map (the reference's
GenericInMemoryCatalog equivalent).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.core.records import (
    KEY_ID_FIELD,
    ROWKIND_DELETE,
    ROWKIND_FIELD,
    TIMESTAMP_FIELD,
    RecordBatch,
)
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.datastream.stream import DataStream
from flink_tpu.table import sql_parser
from flink_tpu.table.optimizer import optimize
from flink_tpu.table.planner import PlannedTable, PlanError, Planner

_INTERNAL_COLS = (TIMESTAMP_FIELD, KEY_ID_FIELD, ROWKIND_FIELD)


from flink_tpu.core.annotations import public_evolving

@public_evolving
class Table:
    """A (possibly unbounded) relational view over a DataStream."""

    def __init__(self, t_env: "StreamTableEnvironment", stream: DataStream,
                 columns: Sequence[str], time_field: Optional[str] = None,
                 upsert_keys: Optional[List[str]] = None,
                 sort_spec=None, limit: Optional[int] = None):
        self.t_env = t_env
        self.stream = stream
        self.columns = list(columns)
        self.time_field = time_field
        self.upsert_keys = upsert_keys
        self.sort_spec = sort_spec
        self.limit = limit

    @staticmethod
    def _from_planned(t_env: "StreamTableEnvironment",
                      planned: PlannedTable) -> "Table":
        return Table(t_env, planned.stream, planned.columns,
                     planned.time_field, planned.upsert_keys,
                     planned.sort_spec, planned.limit)

    def execute(self) -> "TableResult":
        return TableResult(self)

    def to_data_stream(self) -> DataStream:
        return self.stream

    # -- fluent relational API (reference: Table API DSL; every method
    # -- builds the SQL AST and plans through the one Planner+optimizer —
    # -- see flink_tpu/table/fluent.py) --------------------------------------

    _alias: Optional[str] = None

    def alias(self, name: str) -> "Table":
        """Name this table for qualified references in joins
        (reference: Table.as)."""
        out = Table(self.t_env, self.stream, self.columns, self.time_field,
                    self.upsert_keys, self.sort_spec, self.limit)
        out._alias = name
        return out

    def _ref(self):
        from flink_tpu.table.fluent import _InlineTable

        return _InlineTable(self, self._alias)

    def _plan(self, stmt) -> "Table":
        from flink_tpu.table.fluent import _plan

        return Table._from_planned(self.t_env, _plan(self.t_env, stmt))

    def select(self, *exprs) -> "Table":
        from flink_tpu.table import sql_parser as ast
        from flink_tpu.table.fluent import _items

        return self._plan(ast.SelectStmt(items=_items(exprs),
                                         table=self._ref()))

    def where(self, predicate) -> "Table":
        from flink_tpu.table import sql_parser as ast
        from flink_tpu.table.expressions import SelectItem, Star
        from flink_tpu.table.fluent import _expr

        return self._plan(ast.SelectStmt(
            items=[SelectItem(Star(), None)], table=self._ref(),
            where=_expr(predicate)))

    #: reference spelling
    filter = where

    def group_by(self, *keys):
        from flink_tpu.table.fluent import GroupedTable, GroupWindow

        window = None
        plain = []
        for k in keys:
            if isinstance(k, GroupWindow):
                window = k
            else:
                plain.append(k)
        return GroupedTable(self, plain, window)

    def window(self, group_window):
        """Attach a group window; follow with .group_by(..).select(..)
        (reference: Table.window(Tumble...).groupBy(...).select(...))."""
        from flink_tpu.table.fluent import _WindowedTable

        return _WindowedTable(self, group_window)

    def join(self, other: "Table", on) -> "Table":
        return self._join(other, on, "INNER")

    def left_outer_join(self, other: "Table", on) -> "Table":
        return self._join(other, on, "LEFT")

    def _join(self, other: "Table", on, kind: str) -> "Table":
        from flink_tpu.table import sql_parser as ast
        from flink_tpu.table.expressions import SelectItem, Star
        from flink_tpu.table.fluent import _expr

        join = ast.Join(self._ref(), other._ref(), kind, _expr(on))
        return self._plan(ast.SelectStmt(
            items=[SelectItem(Star(), None)], table=join))

    def order_by(self, *exprs) -> "Table":
        """ORDER BY — a materialization-time sort spec on this table
        (exactly what the planner records for SQL ORDER BY), so chaining
        .order_by(...).fetch(n) composes instead of re-planning."""
        from flink_tpu.table.fluent import _order_items

        out = Table(self.t_env, self.stream, self.columns, self.time_field,
                    self.upsert_keys,
                    sort_spec=[(o.expr, o.descending)
                               for o in _order_items(exprs)],
                    limit=self.limit)
        out._alias = self._alias
        return out

    def fetch(self, n: int) -> "Table":
        """LIMIT n (reference: Table.fetch)."""
        out = Table(self.t_env, self.stream, self.columns, self.time_field,
                    self.upsert_keys, sort_spec=self.sort_spec, limit=n)
        out._alias = self._alias
        return out

    def distinct(self) -> "Table":
        from flink_tpu.table import sql_parser as ast
        from flink_tpu.table.expressions import SelectItem, Star

        return self._plan(ast.SelectStmt(
            items=[SelectItem(Star(), None)], table=self._ref(),
            distinct=True))

    def union_all(self, *others: "Table") -> "Table":
        from flink_tpu.table import sql_parser as ast
        from flink_tpu.table.expressions import SelectItem, Star

        selects = [ast.SelectStmt(items=[SelectItem(Star(), None)],
                                  table=t._ref())
                   for t in (self, *others)]
        return self._plan(ast.UnionAll(selects))


@public_evolving
class TableResult:
    """Bounded materialization of a Table (collect-style; the reference's
    TableResult.collect)."""

    def __init__(self, table: Table):
        self.table = table
        self._batch: Optional[RecordBatch] = None

    def to_batch(self) -> RecordBatch:
        if self._batch is None:
            batch = self.table.stream.execute_and_collect()
            self._batch = self._materialize(batch)
        return self._batch

    def collect(self) -> List[dict]:
        batch = self.to_batch()
        rows = batch.to_rows()
        for r in rows:
            for c in _INTERNAL_COLS:
                r.pop(c, None)
        return rows

    def _materialize(self, batch: RecordBatch) -> RecordBatch:
        t = self.table
        if len(batch) and t.upsert_keys is not None:
            # changelog upsert stream: last value per key wins, and a key
            # whose final row is a DELETE has left the table (reference:
            # RowKind.DELETE applied by upsert sinks). An empty key list is
            # a global aggregate — one constant key.
            if not t.upsert_keys:
                batch = batch.slice(len(batch) - 1, len(batch))
            else:
                keys = list(zip(*[batch[k].tolist()
                                  for k in t.upsert_keys])) \
                    if len(t.upsert_keys) > 1 \
                    else batch[t.upsert_keys[0]].tolist()
                last: Dict[object, int] = {}
                for i, k in enumerate(keys):
                    last[k] = i
                idx = np.asarray(sorted(last.values()), dtype=np.int64)
                batch = batch.take(idx)
            if ROWKIND_FIELD in batch.columns and len(batch):
                batch = batch.filter(
                    batch[ROWKIND_FIELD] != ROWKIND_DELETE)
        if len(batch) and t.sort_spec is not None:
            sort_cols = []
            for expr, desc in reversed(t.sort_spec):
                v = np.asarray(expr.eval(batch))
                if v.dtype == object:
                    v = np.array([str(x) for x in v])
                sort_cols.append(-v if desc and v.dtype.kind in "iuf" else v)
            if sort_cols:
                batch = batch.take(np.lexsort(sort_cols))
        if t.limit is not None:
            batch = batch.slice(0, t.limit)
        return batch


@public_evolving
class StreamTableEnvironment:
    def __init__(self, env: Optional[StreamExecutionEnvironment] = None):
        from flink_tpu.ml.models import ModelRegistry

        self.env = env or StreamExecutionEnvironment.get_execution_environment()
        self._catalog: Dict[str, Table] = {}
        #: INSERT INTO targets: name -> (sink, declared columns or None)
        self._sink_tables: Dict[str, tuple] = {}
        #: lookup (dimension) tables: name -> (LookupFunction, columns)
        #: joined via FOR SYSTEM_TIME AS OF (reference: LookupTableSource)
        self._lookup_tables: Dict[str, tuple] = {}
        #: CREATE MODEL / ML_PREDICT catalog (reference: CatalogModel)
        self.models = ModelRegistry()

    def create_lookup_table(self, name: str, lookup_fn,
                            columns: Sequence[str],
                            cache_size: int = 0,
                            cache_ttl_ms=None) -> None:
        """Register a LookupFunction as a dimension table for lookup
        joins: ``JOIN name FOR SYSTEM_TIME AS OF o.rowtime ON ...``
        (reference: a LookupTableSource-backed catalog table; the cache
        maps FLIP-221 'lookup.cache' — opt-in like the reference, with
        ``cache_ttl_ms`` as expireAfterWrite so live dimension updates
        are eventually observed)."""
        self._lookup_tables[name] = (lookup_fn, list(columns),
                                     int(cache_size), cache_ttl_ms)

    def create_temporary_model(self, name: str, model) -> None:
        """Register a Model object for ML_PREDICT (the programmatic form
        of CREATE MODEL; reference: createTemporaryModel)."""
        self.models.register(name, model)

    @staticmethod
    def create(env: Optional[StreamExecutionEnvironment] = None
               ) -> "StreamTableEnvironment":
        return StreamTableEnvironment(env)

    # ------------------------------------------------------------- catalog

    def lookup(self, name: str) -> Table:
        if name not in self._catalog:
            raise PlanError(f"table or view {name!r} is not registered "
                            f"(known: {sorted(self._catalog)})")
        return self._catalog[name]

    def create_temporary_view(self, name: str, source,
                              columns: Optional[Sequence[str]] = None,
                              time_field: Optional[str] = None) -> None:
        """Register a DataStream or Table under a name for SQL queries.

        For a DataStream, ``columns`` lists the visible column names (the
        reference derives them from TypeInformation; batches here are typed
        only at runtime).
        """
        if isinstance(source, Table):
            self._catalog[name] = source
            return
        if columns is None:
            raise PlanError(
                "registering a DataStream as a view requires `columns`")
        self._catalog[name] = Table(self, source, columns, time_field)

    def create_sink_table(self, name: str, sink,
                          columns: Optional[Sequence[str]] = None) -> None:
        """Register a sink as an INSERT INTO target (the reference's
        connector sink table registered via CREATE TABLE ... WITH (...);
        here the sink object is provided programmatically). ``columns``,
        when given, validates and orders the inserted projection."""
        self._sink_tables[name] = (
            sink, list(columns) if columns is not None else None)

    def _create_connector_table(self, stmt) -> None:
        """CREATE TABLE ... WITH ('connector'='...') resolved through the
        connector registry (reference: DynamicTableFactory SPI discovered
        by the 'connector' option)."""
        from flink_tpu.table.connectors import resolve_connector

        connector = stmt.options.get("connector")
        if not connector:
            raise PlanError(
                f"CREATE TABLE {stmt.name}: missing 'connector' option")
        factory = resolve_connector(connector)
        factory(self, stmt)

    def from_data_stream(self, stream: DataStream,
                         columns: Sequence[str],
                         time_field: Optional[str] = None) -> Table:
        return Table(self, stream, columns, time_field)

    def from_collection(self, rows, timestamp_field=None,
                        columns: Optional[Sequence[str]] = None) -> Table:
        rows = list(rows)
        ds = self.env.from_collection(rows, timestamp_field=timestamp_field)
        cols = list(columns) if columns is not None else \
            [c for c in rows[0].keys()]
        return Table(self, ds, cols, timestamp_field)

    # ----------------------------------------------------------------- SQL

    def explain_sql(self, sql: str) -> str:
        """The optimized logical + chained physical plan of a query
        (reference: TableEnvironment.explainSql)."""
        stmt = sql_parser.parse(sql)
        if isinstance(stmt, sql_parser.Explain):
            stmt = stmt.query
        if not isinstance(stmt, (sql_parser.SelectStmt,
                                 sql_parser.UnionAll)):
            raise PlanError(
                "EXPLAIN supports queries (SELECT / UNION ALL), not "
                f"{type(stmt).__name__}")
        return self.explain_sql_statement(sql_parser.Explain(stmt))

    def explain_sql_statement(self, stmt: "sql_parser.Explain") -> str:
        from flink_tpu.table.explain import explain

        optimized = optimize(stmt.query)
        planned = Planner(self).plan_select(optimized)
        return explain(self, optimized, planned)

    def sql_query(self, sql: str) -> Table:
        stmt = sql_parser.parse(sql)
        if not isinstance(stmt, (sql_parser.SelectStmt,
                                 sql_parser.UnionAll)):
            raise PlanError("sql_query expects a SELECT statement")
        planned = Planner(self).plan_select(optimize(stmt))
        return Table._from_planned(self, planned)

    def execute_sql(self, sql: str):
        """Execute a statement (reference: TableEnvironmentImpl.java:936).
        Return value by statement kind: SELECT / UNION ALL -> TableResult;
        INSERT INTO -> the job's JobExecutionResult (runs eagerly);
        EXPLAIN -> the plan text (str); SHOW TABLES -> sorted name list;
        DESCRIBE -> schema dict; CREATE VIEW / CREATE MODEL -> None."""
        stmt = sql_parser.parse(sql)
        if isinstance(stmt, sql_parser.Explain):
            return self.explain_sql_statement(stmt)
        if isinstance(stmt, sql_parser.ShowTables):
            return sorted(self._catalog)
        if isinstance(stmt, sql_parser.Describe):
            t = self.lookup(stmt.name)
            return {
                "name": stmt.name,
                "columns": list(t.columns),
                "time_field": t.time_field,
                "changelog": t.upsert_keys is not None,
                **({"upsert_keys": t.upsert_keys}
                   if t.upsert_keys else {}),
            }
        if isinstance(stmt, sql_parser.CreateModel):
            self.models.create_from_options(stmt.name, stmt.options)
            return None
        if isinstance(stmt, sql_parser.CreateTable):
            self._create_connector_table(stmt)
            return None
        if isinstance(stmt, sql_parser.CreateView):
            planned = Planner(self).plan_select(optimize(stmt.query))
            self._catalog[stmt.name] = Table._from_planned(self, planned)
            return None
        if isinstance(stmt, sql_parser.InsertInto):
            if stmt.table not in self._sink_tables:
                raise PlanError(
                    f"INSERT INTO target {stmt.table!r} is not a "
                    "registered sink table; register one with "
                    "create_sink_table(name, sink, columns=...) "
                    f"(known sinks: {sorted(self._sink_tables)})")
            sink, sink_cols = self._sink_tables[stmt.table]
            planned = Planner(self).plan_select(optimize(stmt.query))
            stream = planned.stream
            sink_pk = getattr(sink, "upsert_keys", None)
            if planned.upsert_keys is not None and sink_pk:
                # upsert sink (PRIMARY KEY ... NOT ENFORCED): materialize
                # the changelog per sink key FIRST — the
                # SinkUpsertMaterializer operator (reference:
                # flink-table-runtime/.../sink/SinkUpsertMaterializer.java).
                # Its list-based algorithm is what makes a changelog
                # whose own key differs from the sink PRIMARY KEY (the
                # reference's main materializer trigger) come out right.
                from flink_tpu.datastream.stream import DataStream
                from flink_tpu.graph.transformations import Transformation
                from flink_tpu.table.upsert_materializer import (
                    UpsertMaterializeOperator,
                )

                from flink_tpu.core.config import StateOptions

                keys = list(sink_pk)
                ttl = self.env.config.get(
                    StateOptions.TABLE_EXEC_STATE_TTL) or None
                t = Transformation(
                    name=f"upsert_materialize({stmt.table})",
                    kind="one_input",
                    operator_factory=lambda keys=keys, ttl=ttl:
                        UpsertMaterializeOperator(keys, ttl_ms=ttl),
                    inputs=[stream.transformation],
                    keyed=True, key_field=keys[0])
                stream = DataStream(self.env, t)
            elif planned.upsert_keys is not None and not getattr(
                    sink, "supports_changelog", False):
                # an updating result written to an append-only sink would
                # record every intermediate per-key update as a fresh row
                # (reference: "Table sink doesn't support consuming update
                # changes" — the planner rejects exactly this)
                raise PlanError(
                    f"INSERT INTO {stmt.table}: the query produces an "
                    "updating (changelog) result but the sink is "
                    "append-only; use a sink with supports_changelog = "
                    "True or a PRIMARY KEY (upsert) table, or make the "
                    "query append-only (e.g. window aggregation instead "
                    "of plain GROUP BY)")
            if sink_cols is not None:
                missing = [c for c in sink_cols
                           if c not in planned.columns]
                if missing:
                    raise PlanError(
                        f"INSERT INTO {stmt.table}: query does not "
                        f"produce sink columns {missing} (query columns: "
                        f"{planned.columns})")
                # changelog consumers keep the row-kind column so they can
                # apply retractions
                cols = tuple(sink_cols) + (
                    (ROWKIND_FIELD,)
                    if planned.upsert_keys is not None else ())
                stream = stream.map(
                    lambda b, cols=cols: b.select(
                        *[c for c in cols if c in b.columns]),
                    name=f"insert_project({stmt.table})")
            stream.sink_to(sink)
            result = self.env.execute(f"insert-into-{stmt.table}")
            return result
        planned = Planner(self).plan_select(optimize(stmt))
        return TableResult(Table._from_planned(self, planned))
