"""Table/SQL API — minimal SQL layer over the DataStream operators.

reference: flink-table/* (TableEnvironmentImpl.executeSql at
flink-table/flink-table-api-java/.../internal/TableEnvironmentImpl.java:936;
planner translate at flink-table-planner/.../delegation/PlannerBase.scala:175).

Re-design: no Calcite, no Janino codegen — the SQL text is parsed by a small
recursive-descent parser, planned directly onto the vectorized DataStream
operators, and "codegen" is JAX tracing of the resulting batched kernels
(SURVEY.md §7.8). Scalar expressions evaluate as vectorized NumPy on host
columns; aggregations run on the device slot table.
"""

from flink_tpu.table.environment import (  # noqa: F401
    StreamTableEnvironment,
    Table,
    TableResult,
)
