"""A small recursive-descent SQL parser.

The reference parses SQL with Apache Calcite (flink-table/flink-sql-parser)
into a full relational algebra. This framework needs the *streaming SQL
subset* that the reference's headline workloads (Nexmark Q5/Q7, GROUP BY HOP)
exercise: SELECT/WHERE/GROUP BY with window TVFs (TUMBLE/HOP/CUMULATE/SESSION,
reference: flink-table-runtime/.../window/tvf/slicing/SliceAssigners.java),
joins with time bounds, Top-N via ROW_NUMBER() OVER, views and INSERT INTO.

Grammar is hand-rolled: tokens -> AST dataclasses in this file +
expression nodes from flink_tpu.table.expressions.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple, Union

from flink_tpu.table.expressions import (
    AGG_NAMES,
    AggCall,
    Between,
    BinaryOp,
    Case,
    Cast,
    Column,
    Expr,
    InList,
    Literal,
    OverCall,
    ScalarFunc,
    SelectItem,
    Star,
    UnaryOp,
)

# ---------------------------------------------------------------------------
# Statement AST
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WindowTVF:
    kind: str                 # TUMBLE | HOP | CUMULATE | SESSION
    table: "TableRef"
    time_col: str
    size_ms: int              # TUMBLE size / HOP size / CUMULATE max / SESSION gap
    slide_ms: Optional[int] = None   # HOP slide / CUMULATE step
    alias: Optional[str] = None


@dataclasses.dataclass
class NamedTable:
    name: str
    alias: Optional[str] = None


@dataclasses.dataclass
class SubQuery:
    query: "SelectStmt"
    alias: Optional[str] = None


@dataclasses.dataclass
class Join:
    left: "TableRef"
    right: "TableRef"
    kind: str            # INNER | LEFT
    condition: Expr
    #: FOR SYSTEM_TIME AS OF <left rowtime> — an event-time TEMPORAL
    #: join against the right side's versions (reference:
    #: StreamExecTemporalJoin); None = regular join
    temporal: "Expr | None" = None


@dataclasses.dataclass
class MLPredictTVF:
    """ML_PREDICT(TABLE t, MODEL m, DESCRIPTOR(f1, f2)) — reference:
    flink-table's ML_PREDICT table function over a CatalogModel."""

    table: "TableRef"
    model: str
    fields: List[str]
    alias: Optional[str] = None


@dataclasses.dataclass
class MatchRecognize:
    """FROM t MATCH_RECOGNIZE (PARTITION BY ... ORDER BY rowtime
    MEASURES ... PATTERN (...) DEFINE ...) — reference:
    StreamExecMatch (flink-table-planner/.../stream/StreamExecMatch.java)
    lowering onto the CEP library's NFA."""

    table: "TableRef"
    partition_by: list            # column names
    order_by: "str | None"        # rowtime column
    #: (func, var, col, alias); func in FIRST/LAST/SUM/AVG/MIN/MAX/COUNT
    measures: list
    #: (var, min_times, max_times-or-None, greedy)
    pattern: list
    define: dict                  # var -> Expr (bool condition)
    after_match: str = "PAST_LAST_ROW"   # or "TO_NEXT_ROW"
    within_ms: "int | None" = None
    alias: "str | None" = None


TableRef = Union[NamedTable, SubQuery, WindowTVF, Join, MLPredictTVF,
                 MatchRecognize]


@dataclasses.dataclass
class OrderItem:
    expr: Expr
    descending: bool = False


@dataclasses.dataclass
class SelectStmt:
    items: List[SelectItem]
    table: TableRef
    where: Optional[Expr] = None
    group_by: List[Expr] = dataclasses.field(default_factory=list)
    having: Optional[Expr] = None
    order_by: List[OrderItem] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None
    distinct: bool = False


@dataclasses.dataclass
class UnionAll:
    """SELECT ... UNION ALL SELECT ... (reference: SqlSetOperator UNION
    ALL; UNION DISTINCT would need a global dedup over an unbounded
    stream and is rejected at parse time). A trailing ORDER BY/LIMIT
    binds to the whole union."""

    selects: List["SelectStmt"]
    order_by: List["OrderItem"] = dataclasses.field(default_factory=list)
    limit: Optional[int] = None


@dataclasses.dataclass
class Explain:
    """EXPLAIN <query> (reference: TableEnvironment.explainSql — prints
    the optimized plan instead of executing)."""

    query: Union["SelectStmt", "UnionAll"]


@dataclasses.dataclass
class ShowTables:
    """SHOW TABLES (reference: TableEnvironment.listTables via SQL)."""


@dataclasses.dataclass
class Describe:
    """DESCRIBE <table> (reference: TableEnvironment SQL DESCRIBE)."""

    name: str


@dataclasses.dataclass
class CreateView:
    name: str
    query: SelectStmt


@dataclasses.dataclass
class CreateModel:
    """CREATE MODEL name WITH ('provider'='python', ...) — reference:
    flink-table CREATE MODEL DDL producing a CatalogModel."""

    name: str
    options: dict


@dataclasses.dataclass
class CreateTable:
    """CREATE TABLE name (col [TYPE], ..., WATERMARK FOR ts AS ts -
    INTERVAL 'n' UNIT) WITH ('connector'='...', ...) — reference:
    connector DDL resolved through the DynamicTableFactory SPI."""

    name: str
    columns: list  # of (name, type-or-None)
    options: dict
    watermark_field: "str | None" = None
    watermark_delay_ms: int = 0
    #: PRIMARY KEY (...) NOT ENFORCED — the upsert key (reference:
    #: upsert-kafka's mandatory primary key)
    primary_key: "list | None" = None


@dataclasses.dataclass
class InsertInto:
    table: str
    query: SelectStmt


Statement = Union[SelectStmt, UnionAll, Explain, ShowTables, Describe, CreateView, CreateModel, CreateTable, InsertInto]

# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*|`[^`]+`)
  | (?P<op><>|!=|<=|>=|\|\||[-+*/%(),.<>={}?])
    """,
    re.VERBOSE,
)

_INTERVAL_MS = {
    "MILLISECOND": 1, "MILLISECONDS": 1,
    "SECOND": 1000, "SECONDS": 1000,
    "MINUTE": 60_000, "MINUTES": 60_000,
    "HOUR": 3_600_000, "HOURS": 3_600_000,
    "DAY": 86_400_000, "DAYS": 86_400_000,
}


@dataclasses.dataclass
class Token:
    kind: str   # num | str | ident | op | end
    value: str

    @property
    def upper(self) -> str:
        return self.value.upper()


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise SqlParseError(f"unexpected character {sql[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        value = m.group()
        if kind == "ident" and value.startswith("`"):
            value = value[1:-1]
        tokens.append(Token(kind, value))
    tokens.append(Token("end", ""))
    return tokens


class SqlParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers ------------------------------------------------------

    def peek(self, offset: int = 0) -> Token:
        return self.tokens[min(self.i + offset, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper in kws

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.i += 1
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SqlParseError(f"expected {kw}, got {self.peek().value!r}")

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.value == op:
            self.i += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlParseError(f"expected {op!r}, got {self.peek().value!r}")

    # -- statements ---------------------------------------------------------

    def parse_statement(self) -> Statement:
        if self.accept_kw("EXPLAIN"):
            if self.accept_kw("PLAN"):  # EXPLAIN PLAN FOR ... spelling
                self.expect_kw("FOR")
            stmt = Explain(self.parse_query())
        elif self.accept_kw("SHOW"):
            self.expect_kw("TABLES")
            stmt = ShowTables()
        elif self.accept_kw("DESCRIBE") or self.accept_kw("DESC"):
            t = self.peek()
            if t.kind != "ident":
                raise SqlParseError(
                    f"DESCRIBE expects a table name, got {t.value!r}")
            stmt = Describe(self.next().value)
        elif self.at_kw("CREATE"):
            stmt = self._create_view()
        elif self.at_kw("INSERT"):
            stmt = self._insert_into()
        else:
            stmt = self.parse_query()
        self.accept_op(";")
        if self.peek().kind != "end":
            raise SqlParseError(f"trailing input at {self.peek().value!r}")
        return stmt

    def _create_view(self) -> Statement:
        self.expect_kw("CREATE")
        self.accept_kw("TEMPORARY")
        if self.accept_kw("MODEL"):
            return self._create_model()
        if self.accept_kw("TABLE"):
            return self._create_table()
        self.expect_kw("VIEW")
        name = self.next().value
        self.expect_kw("AS")
        return CreateView(name, self.parse_query())

    def _create_table(self) -> CreateTable:
        name = self.next().value
        columns: list = []
        wm_field = None
        wm_delay = 0
        primary_key: list = []
        self.expect_op("(")
        while True:
            if self.accept_kw("PRIMARY"):
                # PRIMARY KEY (k [, ...]) NOT ENFORCED — declares the
                # upsert key (reference: upsert-kafka requires a PRIMARY
                # KEY; enforcement is impossible on a changelog, hence
                # the mandatory NOT ENFORCED)
                self.expect_kw("KEY")
                self.expect_op("(")
                while True:
                    primary_key.append(self.next().value)
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                self.expect_kw("NOT")
                self.expect_kw("ENFORCED")
            elif self.accept_kw("WATERMARK"):
                self.expect_kw("FOR")
                wm_field = self.next().value
                self.expect_kw("AS")
                # accept `ts` or `ts - INTERVAL 'n' UNIT`
                ref = self.next().value
                if ref != wm_field:
                    raise SqlParseError(
                        "WATERMARK expression must reference the "
                        f"watermark column {wm_field!r}")
                if self.accept_op("-"):
                    self.expect_kw("INTERVAL")
                    t = self.next()
                    if t.kind not in ("str", "num"):
                        raise SqlParseError(
                            "INTERVAL expects a quoted amount")
                    amount = float(t.value[1:-1] if t.kind == "str"
                                   else t.value)
                    unit = self.next().upper
                    if unit not in _INTERVAL_MS:
                        raise SqlParseError(
                            f"unknown interval unit {unit!r}")
                    wm_delay = int(amount * _INTERVAL_MS[unit])
            else:
                col = self.next()
                if col.kind != "ident":
                    raise SqlParseError(
                        f"expected a column name, got {col.value!r}")
                # optional type + modifiers (BIGINT, DECIMAL(10, 2),
                # TIMESTAMP(3), NOT NULL ...): consumed and recorded but
                # not enforced — the runtime is dtype-driven
                ctype_parts = []
                while self.peek().kind == "ident":
                    ctype_parts.append(self.next().value)
                    if self.accept_op("("):
                        depth = 1
                        while depth:
                            tok = self.next()
                            if tok.kind == "op" and tok.value == "(":
                                depth += 1
                            elif tok.kind == "op" and tok.value == ")":
                                depth -= 1
                columns.append((col.value,
                                " ".join(ctype_parts) or None))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        self.expect_kw("WITH")
        self.expect_op("(")
        options = {}
        while True:
            k = self.next()
            if k.kind != "str":
                raise SqlParseError("table options are 'key' = 'value'")
            self.expect_op("=")
            v = self.next()
            if v.kind != "str":
                raise SqlParseError("table options are 'key' = 'value'")
            options[k.value[1:-1]] = v.value[1:-1]
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return CreateTable(name, columns, options,
                           watermark_field=wm_field,
                           watermark_delay_ms=wm_delay,
                           primary_key=primary_key or None)

    def _create_model(self) -> CreateModel:
        name = self.next().value
        self.expect_kw("WITH")
        self.expect_op("(")
        options = {}
        while True:
            k = self.next()
            if k.kind != "str":
                raise SqlParseError("model options are 'key' = 'value'")
            self.expect_op("=")
            v = self.next()
            if v.kind != "str":
                raise SqlParseError("model options are 'key' = 'value'")
            options[k.value[1:-1]] = v.value[1:-1]
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return CreateModel(name, options)

    def _insert_into(self) -> InsertInto:
        self.expect_kw("INSERT")
        self.expect_kw("INTO")
        name = self.next().value
        return InsertInto(name, self.parse_query())

    # -- SELECT -------------------------------------------------------------

    def parse_query(self):
        """One SELECT or a UNION ALL chain."""
        first = self.parse_select()
        if not self.at_kw("UNION"):
            return first
        selects = [first]
        while self.accept_kw("UNION"):
            if not self.accept_kw("ALL"):
                raise SqlParseError(
                    "only UNION ALL is supported (UNION DISTINCT would "
                    "require a global dedup over an unbounded stream)")
            selects.append(self.parse_select())
        for s in selects[:-1]:
            if s.order_by or s.limit is not None:
                raise SqlParseError(
                    "ORDER BY / LIMIT inside a UNION branch is not "
                    "supported; place it after the last branch")
        last = selects[-1]
        order_by, limit = last.order_by, last.limit
        selects[-1] = dataclasses.replace(last, order_by=[], limit=None)
        return UnionAll(selects, order_by, limit)

    def parse_select(self) -> SelectStmt:
        self.expect_kw("SELECT")
        distinct = self.accept_kw("DISTINCT")
        items = [self._select_item()]
        while self.accept_op(","):
            items.append(self._select_item())
        self.expect_kw("FROM")
        table = self._table_ref()
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        group_by: List[Expr] = []
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.parse_expr())
            while self.accept_op(","):
                group_by.append(self.parse_expr())
        having = self.parse_expr() if self.accept_kw("HAVING") else None
        order_by: List[OrderItem] = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self._order_item())
            while self.accept_op(","):
                order_by.append(self._order_item())
        limit = None
        if self.accept_kw("LIMIT"):
            limit = int(self.next().value)
        return SelectStmt(items, table, where, group_by, having, order_by,
                          limit, distinct)

    def _order_item(self) -> OrderItem:
        e = self.parse_expr()
        desc = False
        if self.accept_kw("DESC"):
            desc = True
        else:
            self.accept_kw("ASC")
        return OrderItem(e, desc)

    def _select_item(self) -> SelectItem:
        if self.accept_op("*"):
            return SelectItem(Star())
        e = self.parse_expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self.next().value
        elif (self.peek().kind == "ident"
              and self.peek().upper not in _CLAUSE_KWS):
            alias = self.next().value
        return SelectItem(e, alias)

    # -- FROM / joins -------------------------------------------------------

    def _table_ref(self) -> TableRef:
        left = self._table_primary()
        while True:
            kind = None
            if self.accept_kw("JOIN"):
                kind = "INNER"
            elif self.at_kw("INNER") and self.peek(1).upper == "JOIN":
                self.i += 2
                kind = "INNER"
            elif self.at_kw("LEFT"):
                self.i += 1
                self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                kind = "LEFT"
            else:
                return left
            right = self._table_primary()
            temporal = None
            if self.accept_kw("FOR"):
                # JOIN versioned FOR SYSTEM_TIME AS OF o.rowtime AS v
                self.expect_kw("SYSTEM_TIME")
                self.expect_kw("AS")
                self.expect_kw("OF")
                temporal = self.parse_expr()
                alias = self._opt_alias()
                if alias is not None:
                    if not hasattr(right, "alias"):
                        raise SqlParseError(
                            "cannot alias this temporal join input")
                    right = dataclasses.replace(right, alias=alias)
            self.expect_kw("ON")
            cond = self.parse_expr()
            left = Join(left, right, kind, cond, temporal=temporal)

    def _table_primary(self) -> TableRef:
        if self.at_kw("TABLE") and self.peek(1).value == "(":
            return self._window_tvf()
        if self.peek().upper == "ML_PREDICT" and self.peek(1).value == "(":
            return self._ml_predict_tvf()
        if self.accept_op("("):
            q = self.parse_query()
            self.expect_op(")")
            alias = self._opt_alias()
            return SubQuery(q, alias)
        name = self.next().value
        ref = NamedTable(name, self._opt_alias())
        if self.at_kw("MATCH_RECOGNIZE"):
            return self._match_recognize(ref)
        return ref

    def _match_recognize(self, table: TableRef) -> MatchRecognize:
        """MATCH_RECOGNIZE (PARTITION BY ... ORDER BY ... MEASURES ...
        [ONE ROW PER MATCH] [AFTER MATCH SKIP ...] PATTERN (...)
        [WITHIN INTERVAL ...] DEFINE ...) [AS alias]."""
        self.expect_kw("MATCH_RECOGNIZE")
        self.expect_op("(")
        partition: List[str] = []
        order = None
        measures: List[tuple] = []
        after = "PAST_LAST_ROW"
        pattern: List[tuple] = []
        define: dict = {}
        within = None
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition.append(self.next().value)
            while self.accept_op(","):
                partition.append(self.next().value)
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order = self.next().value
            self.accept_kw("ASC")
        if self.accept_kw("MEASURES"):
            while True:
                measures.append(self._measure_item())
                if not self.accept_op(","):
                    break
        if self.accept_kw("ONE"):
            self.expect_kw("ROW")
            self.expect_kw("PER")
            self.expect_kw("MATCH")
        if self.accept_kw("AFTER"):
            self.expect_kw("MATCH")
            self.expect_kw("SKIP")
            if self.accept_kw("PAST"):
                self.expect_kw("LAST")
                self.expect_kw("ROW")
                after = "PAST_LAST_ROW"
            elif self.accept_kw("TO"):
                self.expect_kw("NEXT")
                self.expect_kw("ROW")
                after = "TO_NEXT_ROW"
            else:
                raise SqlParseError(
                    "AFTER MATCH SKIP supports PAST LAST ROW / "
                    "TO NEXT ROW")
        self.expect_kw("PATTERN")
        self.expect_op("(")
        while not self.accept_op(")"):
            pattern.append(self._pattern_var())
        if self.accept_kw("WITHIN"):
            self.expect_kw("INTERVAL")
            t = self.next()
            if t.kind not in ("str", "num"):
                raise SqlParseError("INTERVAL expects a quoted amount")
            amount = float(t.value[1:-1] if t.kind == "str" else t.value)
            unit = self.next().upper
            if unit not in _INTERVAL_MS:
                raise SqlParseError(f"unknown interval unit {unit!r}")
            within = int(amount * _INTERVAL_MS[unit])
        if self.accept_kw("DEFINE"):
            while True:
                var = self.next().value
                self.expect_kw("AS")
                define[var.upper()] = self.parse_expr()
                if not self.accept_op(","):
                    break
        self.expect_op(")")
        return MatchRecognize(table, partition, order, measures, pattern,
                              define, after_match=after,
                              within_ms=within, alias=self._opt_alias())

    def _measure_item(self) -> tuple:
        """FIRST(V.c) | LAST(V.c) | SUM/AVG/MIN/MAX/COUNT(V.c) | V.c,
        each AS alias."""
        name = self.next()
        func = "LAST"
        if name.upper in ("FIRST", "LAST", "SUM", "AVG", "MIN", "MAX",
                          "COUNT") and self.peek().value == "(":
            func = name.upper
            self.expect_op("(")
            var = self.next().value
            self.expect_op(".")
            col = self.next().value
            self.expect_op(")")
        else:
            var = name.value
            self.expect_op(".")
            col = self.next().value
        self.expect_kw("AS")
        alias = self.next().value
        return (func, var.upper(), col, alias)

    def _pattern_var(self) -> tuple:
        """A pattern variable with its quantifier: V V* V+ V? V{n} V{n,}
        V{n,m}, with a trailing '?' marking RELUCTANT (SQL row-pattern
        quantifiers are greedy by default)."""
        var = self.next()
        if var.kind != "ident":
            raise SqlParseError(
                f"expected a pattern variable, got {var.value!r}")
        mn, mx = 1, 1
        loop = False
        if self.accept_op("*"):
            mn, mx, loop = 0, None, True
        elif self.accept_op("+"):
            mn, mx, loop = 1, None, True
        elif self.accept_op("?"):
            mn, mx = 0, 1
        elif self.accept_op("{"):
            t = self.next()
            if t.kind != "num":
                raise SqlParseError("pattern quantifier expects a count")
            mn = int(float(t.value))
            mx = mn
            if self.accept_op(","):
                if self.accept_op("}"):
                    mx, loop = None, True
                else:
                    t2 = self.next()
                    if t2.kind != "num":
                        raise SqlParseError(
                            "pattern quantifier expects a count")
                    mx = int(float(t2.value))
                    # exact {n} has no take/stop freedom — greedy is
                    # meaningless (and harmful) for it
                    loop = mx != mn
                    self.expect_op("}")
            else:
                loop = False
                self.expect_op("}")
        else:
            return (var.value.upper(), 1, 1, False)
        greedy = loop
        if self.accept_op("?"):
            greedy = False  # reluctant quantifier
        return (var.value.upper(), mn, mx, greedy)

    def _opt_alias(self) -> Optional[str]:
        if self.accept_kw("AS"):
            return self.next().value
        if (self.peek().kind == "ident"
                and self.peek().upper not in _CLAUSE_KWS):
            return self.next().value
        return None

    def _window_tvf(self) -> WindowTVF:
        self.expect_kw("TABLE")
        self.expect_op("(")
        kind = self.next().upper
        if kind not in ("TUMBLE", "HOP", "CUMULATE", "SESSION"):
            raise SqlParseError(f"unknown window TVF {kind}")
        self.expect_op("(")
        # positional `TABLE t` or named `DATA => TABLE t`
        if self.accept_kw("DATA"):
            self.expect_op("=")
            self.expect_op(">")
        self.expect_kw("TABLE")
        inner = NamedTable(self.next().value)
        self.expect_op(",")
        self.expect_kw("DESCRIPTOR")
        self.expect_op("(")
        time_col = self.next().value
        self.expect_op(")")
        self.expect_op(",")
        first = self._interval_ms()
        second = None
        if self.accept_op(","):
            second = self._interval_ms()
        self.expect_op(")")
        self.expect_op(")")
        alias = self._opt_alias()
        # argument order per the reference's TVF definitions:
        # HOP(data, desc, slide, size); CUMULATE(data, desc, step, max)
        if kind in ("HOP", "CUMULATE"):
            if second is None:
                raise SqlParseError(f"{kind} needs two intervals")
            slide, size = first, second
            return WindowTVF(kind, inner, time_col, size, slide, alias)
        return WindowTVF(kind, inner, time_col, first, None, alias)

    def _named_arg(self, *names: str) -> None:
        """Consume an optional ``NAME =>`` prefix (reference: ML_PREDICT's
        INPUT/DATA, MODEL, ARGS named arguments)."""
        if self.peek().upper in names and self.peek(1).value == "=":
            self.next()
            self.expect_op("=")
            self.expect_op(">")

    def _ml_predict_tvf(self) -> MLPredictTVF:
        """ML_PREDICT([INPUT|DATA =>] TABLE t, [MODEL =>] MODEL? m,
        [ARGS =>] DESCRIPTOR(f1, f2, ...))."""
        self.next()  # ML_PREDICT
        self.expect_op("(")
        self._named_arg("INPUT", "DATA")
        self.expect_kw("TABLE")
        inner: TableRef
        if self.accept_op("("):
            q = self.parse_select()
            self.expect_op(")")
            inner = SubQuery(q)
        else:
            inner = NamedTable(self.next().value)
        self.expect_op(",")
        # named form `MODEL => m` has no second MODEL keyword; positional
        # form is `MODEL m`
        if self.peek().upper == "MODEL" and self.peek(1).value == "=":
            self._named_arg("MODEL")
        else:
            self.expect_kw("MODEL")
        model = self.next().value
        self.expect_op(",")
        self._named_arg("ARGS")
        self.expect_kw("DESCRIPTOR")
        self.expect_op("(")
        fields = [self.next().value]
        while self.accept_op(","):
            fields.append(self.next().value)
        self.expect_op(")")
        self.expect_op(")")
        return MLPredictTVF(inner, model, fields, self._opt_alias())

    def _interval_ms(self) -> int:
        self.expect_kw("INTERVAL")
        tok = self.next()
        if tok.kind != "str":
            raise SqlParseError("INTERVAL value must be a quoted string")
        amount = float(tok.value[1:-1])
        unit = self.next().upper
        if unit not in _INTERVAL_MS:
            raise SqlParseError(f"unknown interval unit {unit}")
        return int(amount * _INTERVAL_MS[unit])

    # -- expressions (precedence climbing) ----------------------------------

    def parse_expr(self) -> Expr:
        return self._or_expr()

    def _or_expr(self) -> Expr:
        e = self._and_expr()
        while self.accept_kw("OR"):
            e = BinaryOp("OR", e, self._and_expr())
        return e

    def _and_expr(self) -> Expr:
        e = self._not_expr()
        while self.accept_kw("AND"):
            e = BinaryOp("AND", e, self._not_expr())
        return e

    def _not_expr(self) -> Expr:
        if self.accept_kw("NOT"):
            return UnaryOp("NOT", self._not_expr())
        return self._comparison()

    def _comparison(self) -> Expr:
        e = self._additive()
        if self.accept_kw("BETWEEN"):
            low = self._additive()
            self.expect_kw("AND")
            return Between(e, low, self._additive())
        if self.accept_kw("IN") or (self.at_kw("NOT")
                                    and self.peek(1).upper == "IN"):
            negated = False
            if self.at_kw("IN"):
                self.i += 1
            else:
                self.i += 2
                negated = True
            self.expect_op("(")
            opts = [self._literal_value()]
            while self.accept_op(","):
                opts.append(self._literal_value())
            self.expect_op(")")
            return InList(e, tuple(opts), negated)
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            return BinaryOp(t.value, e, self._additive())
        return e

    def _literal_value(self):
        t = self.next()
        if t.kind == "num":
            return float(t.value) if "." in t.value else int(t.value)
        if t.kind == "str":
            return t.value[1:-1].replace("''", "'")
        raise SqlParseError(f"expected literal, got {t.value!r}")

    def _additive(self) -> Expr:
        e = self._multiplicative()
        while True:
            if self.accept_op("+"):
                e = BinaryOp("+", e, self._multiplicative())
            elif self.accept_op("-"):
                e = BinaryOp("-", e, self._multiplicative())
            else:
                return e

    def _multiplicative(self) -> Expr:
        e = self._unary()
        while True:
            if self.accept_op("*"):
                e = BinaryOp("*", e, self._unary())
            elif self.accept_op("/"):
                e = BinaryOp("/", e, self._unary())
            elif self.accept_op("%"):
                e = BinaryOp("%", e, self._unary())
            else:
                return e

    def _unary(self) -> Expr:
        if self.accept_op("-"):
            return UnaryOp("-", self._unary())
        return self._primary()

    def _primary(self) -> Expr:
        t = self.peek()
        if t.kind == "num":
            self.next()
            v = float(t.value) if ("." in t.value or "e" in t.value.lower()) \
                else int(t.value)
            return Literal(v)
        if t.kind == "str":
            self.next()
            return Literal(t.value[1:-1].replace("''", "'"))
        if t.kind == "op" and t.value == "(":
            self.next()
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if self.at_kw("INTERVAL"):
            return Literal(self._interval_ms())
        if self.at_kw("CASE"):
            return self._case_expr()
        if self.at_kw("CAST"):
            self.next()
            self.expect_op("(")
            inner = self.parse_expr()
            self.expect_kw("AS")
            type_name = self.next().upper
            # swallow precision like VARCHAR(255)
            if self.accept_op("("):
                while not self.accept_op(")"):
                    self.next()
            self.expect_op(")")
            return Cast(inner, type_name)
        if self.at_kw("TRUE"):
            self.next()
            return Literal(True)
        if self.at_kw("FALSE"):
            self.next()
            return Literal(False)
        if t.kind == "ident":
            return self._identifier_or_call()
        raise SqlParseError(f"unexpected token {t.value!r}")

    def _case_expr(self) -> Case:
        self.expect_kw("CASE")
        whens = []
        while self.accept_kw("WHEN"):
            c = self.parse_expr()
            self.expect_kw("THEN")
            whens.append((c, self.parse_expr()))
        default = self.parse_expr() if self.accept_kw("ELSE") else None
        self.expect_kw("END")
        return Case(tuple(whens), default)

    def _identifier_or_call(self) -> Expr:
        name = self.next().value
        upper = name.upper()
        if self.peek().kind == "op" and self.peek().value == "(":
            self.next()  # (
            if upper in AGG_NAMES:
                distinct = self.accept_kw("DISTINCT")
                if self.accept_op("*"):
                    self.expect_op(")")
                    arg = None
                else:
                    arg = self.parse_expr()
                    self.expect_op(")")
                if self.at_kw("OVER"):
                    if distinct:
                        raise SqlParseError(
                            "DISTINCT is not supported in OVER "
                            "aggregates")
                    return self._over_agg_clause(upper, arg)
                return AggCall(upper, arg, distinct)
            if upper in ("ROW_NUMBER", "RANK"):
                self.expect_op(")")
                return self._over_clause(upper)
            args = []
            if not self.accept_op(")"):
                args.append(self.parse_expr())
                while self.accept_op(","):
                    args.append(self.parse_expr())
                self.expect_op(")")
            return ScalarFunc(upper, tuple(args))
        if self.accept_op("."):
            col = self.next().value
            return Column(col, table=name)
        return Column(name)

    def _partition_order(self):
        """The shared OVER-window prefix: PARTITION BY ... ORDER BY ...
        (caller has consumed OVER and the opening paren)."""
        partition: List[Expr] = []
        order: List[Tuple[Expr, bool]] = []
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition.append(self.parse_expr())
            while self.accept_op(","):
                partition.append(self.parse_expr())
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                e = self.parse_expr()
                desc = False
                if self.accept_kw("DESC"):
                    desc = True
                else:
                    self.accept_kw("ASC")
                order.append((e, desc))
                if not self.accept_op(","):
                    break
        return tuple(partition), tuple(order)

    def _over_clause(self, func: str) -> OverCall:
        self.expect_kw("OVER")
        self.expect_op("(")
        partition, order = self._partition_order()
        self.expect_op(")")
        return OverCall(func, partition, order)

    def _over_agg_clause(self, func: str, arg):
        """agg(x) OVER (PARTITION BY ... ORDER BY rowtime
        [ROWS|RANGE BETWEEN <n | INTERVAL 'x' UNIT | UNBOUNDED>
        PRECEDING AND CURRENT ROW]) — reference:
        StreamExecOverAggregate. No frame clause = RANGE UNBOUNDED
        PRECEDING (the SQL default)."""
        from flink_tpu.table.expressions import OverAgg

        self.expect_kw("OVER")
        self.expect_op("(")
        partition, order = self._partition_order()
        mode, preceding = "RANGE", None
        if self.at_kw("ROWS", "RANGE"):
            mode = self.next().upper
            self.expect_kw("BETWEEN")
            if self.accept_kw("UNBOUNDED"):
                preceding = None
            elif mode == "ROWS":
                t = self.next()
                if t.kind != "num" or not float(t.value).is_integer():
                    raise SqlParseError(
                        "ROWS BETWEEN expects a whole row count, got "
                        f"{t.value!r}")
                preceding = int(float(t.value))
            else:
                self.expect_kw("INTERVAL")
                t = self.next()
                if t.kind not in ("str", "num"):
                    raise SqlParseError("INTERVAL expects a quoted amount")
                amount = float(t.value[1:-1] if t.kind == "str"
                               else t.value)
                unit = self.next().upper
                if unit not in _INTERVAL_MS:
                    raise SqlParseError(
                        f"unknown interval unit {unit!r}")
                preceding = int(amount * _INTERVAL_MS[unit])
            self.expect_kw("PRECEDING")
            self.expect_kw("AND")
            self.expect_kw("CURRENT")
            self.expect_kw("ROW")
        self.expect_op(")")
        return OverAgg(func, arg, partition, order,
                       mode=mode, preceding=preceding)


_CLAUSE_KWS = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "JOIN", "INNER",
    "LEFT", "RIGHT", "FULL", "OUTER", "ON", "AS", "AND", "OR", "NOT",
    "UNION", "SELECT", "BY", "ASC", "DESC", "BETWEEN", "IN", "CASE", "WHEN",
    "THEN", "ELSE", "END", "TABLE", "INTERVAL", "HAVING", "CROSS",
    "MATCH_RECOGNIZE", "FOR",
}


def parse(sql: str) -> Statement:
    return Parser(sql).parse_statement()
