"""Fluent (programmatic) Table API.

reference: flink-table-api-java's Table/Expressions DSL —
``table.where($("price").isGreater(10)).groupBy($("auction"))
.select($("auction"), $("price").sum().as("total"))`` and the
Tumble/Slide/Session group-window helpers (Expressions.java, Tumble.java).

Re-design: every fluent call builds the SAME AST the SQL parser produces
(flink_tpu/table/sql_parser.py expressions + SelectStmt), then plans
through the one Planner — so the rule-based optimizer, retraction
semantics, window TVF translation, and rank patterns all apply
identically whether a query arrived as a string or as method calls.
``col("x")`` is the expression entry point (PyFlink's ``col``); Python
operators build BinaryOp/UnaryOp trees; ``.sum/.avg/...`` build AggCalls.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from flink_tpu.table import sql_parser as ast
from flink_tpu.table.expressions import (
    AggCall,
    BinaryOp,
    Column,
    Expr,
    Literal,
    SelectItem,
    Star,
    UnaryOp,
)

from flink_tpu.core.annotations import public_evolving


class FluentExpr:
    """Wraps an Expr with Python-operator sugar."""

    def __init__(self, expr: Expr, alias: Optional[str] = None):
        self.expr = expr
        self._alias = alias

    # -- naming --------------------------------------------------------------

    def alias(self, name: str) -> "FluentExpr":
        return FluentExpr(self.expr, name)

    #: PyFlink spelling
    def as_(self, name: str) -> "FluentExpr":
        return self.alias(name)

    def _item(self) -> SelectItem:
        return SelectItem(self.expr, self._alias)

    # -- arithmetic / comparison --------------------------------------------

    def _bin(self, op: str, other) -> "FluentExpr":
        return FluentExpr(BinaryOp(op, self.expr, _expr(other)))

    def _rbin(self, op: str, other) -> "FluentExpr":
        return FluentExpr(BinaryOp(op, _expr(other), self.expr))

    def __add__(self, o):
        return self._bin("+", o)

    def __radd__(self, o):
        return self._rbin("+", o)

    def __sub__(self, o):
        return self._bin("-", o)

    def __rsub__(self, o):
        return self._rbin("-", o)

    def __mul__(self, o):
        return self._bin("*", o)

    def __rmul__(self, o):
        return self._rbin("*", o)

    def __truediv__(self, o):
        return self._bin("/", o)

    def __rtruediv__(self, o):
        return self._rbin("/", o)

    def __mod__(self, o):
        return self._bin("%", o)

    def __rmod__(self, o):
        return self._rbin("%", o)

    def __gt__(self, o):
        return self._bin(">", o)

    def __ge__(self, o):
        return self._bin(">=", o)

    def __lt__(self, o):
        return self._bin("<", o)

    def __le__(self, o):
        return self._bin("<=", o)

    def __eq__(self, o):  # noqa: PYI032 - DSL equality builds a predicate
        return self._bin("=", o)

    def __ne__(self, o):
        return self._bin("<>", o)

    def __and__(self, o):
        return self._bin("AND", o)

    def __or__(self, o):
        return self._bin("OR", o)

    def __invert__(self):
        return FluentExpr(UnaryOp("NOT", self.expr))

    def __neg__(self):
        return FluentExpr(UnaryOp("-", self.expr))

    __hash__ = None  # predicates are not hashable keys

    # -- ordering ------------------------------------------------------------

    def desc(self) -> "_Ordered":
        return _Ordered(self.expr, True)

    def asc(self) -> "_Ordered":
        return _Ordered(self.expr, False)

    # -- aggregates ----------------------------------------------------------

    def _agg(self, func: str) -> "FluentExpr":
        return FluentExpr(AggCall(func, self.expr))

    def sum(self) -> "FluentExpr":
        return self._agg("SUM")

    def min(self) -> "FluentExpr":
        return self._agg("MIN")

    def max(self) -> "FluentExpr":
        return self._agg("MAX")

    def avg(self) -> "FluentExpr":
        return self._agg("AVG")

    def count(self) -> "FluentExpr":
        return self._agg("COUNT")


@public_evolving
def col(name: str) -> FluentExpr:
    """Column reference (reference: Expressions.$ / pyflink col).
    ``col("L.k")`` builds a table-qualified reference, as the SQL parser
    would — required to disambiguate same-named join keys."""
    if "." in name:
        table, _, column = name.partition(".")
        return FluentExpr(Column(column, table))
    return FluentExpr(Column(name))


@public_evolving
def lit(value) -> FluentExpr:
    return FluentExpr(Literal(value))


def count_star() -> FluentExpr:
    """COUNT(*) (reference: lit(1).count / $.count)."""
    return FluentExpr(AggCall("COUNT", None))


def _expr(x) -> Expr:
    if isinstance(x, FluentExpr):
        return x.expr
    if isinstance(x, Expr):
        return x
    return Literal(x)


def _items(exprs: Sequence) -> List[SelectItem]:
    out = []
    for e in exprs:
        if isinstance(e, FluentExpr):
            out.append(e._item())
        elif isinstance(e, str):
            out.append(SelectItem(Star(), None) if e == "*"
                       else SelectItem(Column(e), None))
        else:
            out.append(SelectItem(_expr(e), None))
    return out


# ---------------------------------------------------------------------------
# group windows (reference: Tumble/Slide/Session over/on/alias builders)
# ---------------------------------------------------------------------------


class GroupWindow:
    """Immutable builder (reference: Tumble/Slide/Session builders return
    fresh objects — a shared prefix must not mutate across queries)."""

    def __init__(self, kind: str, size_ms: int,
                 slide_ms: Optional[int] = None,
                 time_col: Optional[str] = None,
                 name: Optional[str] = None):
        self.kind = kind
        self.size_ms = size_ms
        self.slide_ms = slide_ms
        self.time_col = time_col
        self._name = name

    def on(self, time_col) -> "GroupWindow":
        tc = time_col.expr.name \
            if isinstance(time_col, FluentExpr) else str(time_col)
        return GroupWindow(self.kind, self.size_ms, self.slide_ms,
                           tc, self._name)

    def alias(self, name: str) -> "GroupWindow":
        return GroupWindow(self.kind, self.size_ms, self.slide_ms,
                           self.time_col, name)


class Tumble:
    @staticmethod
    def over(size_ms: int) -> GroupWindow:
        return GroupWindow("TUMBLE", size_ms)


class Slide:
    @staticmethod
    def over(size_ms: int, every_ms: int) -> GroupWindow:
        return GroupWindow("HOP", size_ms, every_ms)


class Session:
    @staticmethod
    def with_gap(gap_ms: int) -> GroupWindow:
        return GroupWindow("SESSION", gap_ms)


# ---------------------------------------------------------------------------
# fluent table mixin — implementation of Table.select/where/group_by/...
# ---------------------------------------------------------------------------


class _InlineTable:
    """AST table ref wrapping a live Table object (the fluent API's FROM
    clause — no catalog name needed)."""

    def __init__(self, table, alias: Optional[str] = None):
        self.table = table
        self.alias = alias


def _plan(t_env, stmt: ast.SelectStmt):
    from flink_tpu.table.optimizer import optimize
    from flink_tpu.table.planner import Planner

    return Planner(t_env).plan_select(optimize(stmt))


class _Ordered:
    def __init__(self, expr: Expr, descending: bool):
        self.expr = expr
        self.descending = descending


def _order_items(exprs: Sequence) -> List["ast.OrderItem"]:
    out = []
    for e in exprs:
        if isinstance(e, _Ordered):
            out.append(ast.OrderItem(e.expr, e.descending))
        else:
            out.append(ast.OrderItem(_expr(
                e if not isinstance(e, str) else Column(e)), False))
    return out


class _WindowedTable:
    """Table.window(Tumble...) — awaits .group_by(...) (reference:
    WindowedTable)."""

    def __init__(self, table, window: GroupWindow):
        self._table = table
        self._window = window

    def group_by(self, *keys) -> "GroupedTable":
        plain = []
        for k in keys:
            if isinstance(k, GroupWindow):
                continue
            if isinstance(k, str) and k == self._window._name:
                continue  # the window pseudo-column: implied grouping
            if isinstance(k, FluentExpr) and isinstance(k.expr, Column) \
                    and k.expr.name == self._window._name:
                continue
            plain.append(k)
        return GroupedTable(self._table, plain, self._window)


class GroupedTable:
    """Result of Table.group_by — awaits .select(...) (reference:
    GroupedTable / WindowGroupedTable)."""

    def __init__(self, table, keys: Sequence,
                 window: Optional[GroupWindow] = None):
        self._table = table
        self._keys = list(keys)
        self._window = window

    def select(self, *exprs):
        from flink_tpu.table.environment import Table

        t = self._table
        ref: ast.TableRef = t._ref()  # preserves the table's alias
        group_by: List[Expr] = []
        items = _items(exprs)
        if self._window is not None:
            w = self._window
            ref = ast.WindowTVF(w.kind, ref, w.time_col, w.size_ms,
                                w.slide_ms)
            group_by.extend([Column("window_start"), Column("window_end")])
        for k in self._keys:
            e = _expr(k if not isinstance(k, str) else Column(k))
            if isinstance(e, Column) and self._window is not None \
                    and self._window._name is not None \
                    and e.name == self._window._name:
                continue  # the window pseudo-column is the TVF grouping
            group_by.append(e)
        stmt = ast.SelectStmt(items=items, table=ref, group_by=group_by)
        return Table._from_planned(t.t_env, _plan(t.t_env, stmt))
