"""SQL connector registry — CREATE TABLE ... WITH ('connector'='...').

reference: the DynamicTableFactory SPI
(flink-table/flink-table-common/src/main/java/org/apache/flink/table/factories/DynamicTableFactory.java:1)
discovered by the 'connector' option, producing ScanTableSource /
DynamicTableSink per table. Re-design: a factory is a plain callable
``factory(table_env, CreateTable) -> None`` that registers the table as a
source view and/or INSERT INTO sink on the environment; register custom
connectors with :func:`register_connector`.
"""

from __future__ import annotations

from typing import Callable, Dict

_CONNECTORS: Dict[str, Callable] = {}


def register_connector(name: str, factory: Callable) -> None:
    """``factory(table_env, stmt)`` wires a CreateTable statement into the
    environment (source view, sink table, or both)."""
    _CONNECTORS[name.lower()] = factory


def resolve_connector(name: str) -> Callable:
    factory = _CONNECTORS.get(name.lower())
    if factory is None:
        from flink_tpu.table.environment import PlanError

        raise PlanError(
            f"unknown connector {name!r} (registered: "
            f"{sorted(_CONNECTORS)}); add one with "
            "flink_tpu.table.connectors.register_connector")
    return factory


def _opt_bool(options: dict, key: str, default: bool) -> bool:
    v = options.get(key)
    if v is None:
        return default
    return str(v).lower() in ("true", "1", "yes")


def _kafka_factory(tenv, stmt) -> None:
    """'kafka': partitioned source (bounded or unbounded scan) AND
    partitioned append sink under the same table name — the reference's
    kafka tables are readable and writable too."""
    from flink_tpu.connectors.kafka import KafkaSink, KafkaSource
    from flink_tpu.table.environment import PlanError

    opts = stmt.options
    topic = opts.get("topic")
    if not topic:
        raise PlanError(f"CREATE TABLE {stmt.name}: kafka connector "
                        "requires a 'topic' option")
    broker_name = opts.get("broker", "default")
    bounded = _opt_bool(opts, "scan.bounded", True)
    cols = [c for c, _ in stmt.columns]
    col_types = [t for _, t in stmt.columns]
    wm_field = stmt.watermark_field
    deser = ser = None
    fmt = opts.get("format")
    if fmt:
        # the format seam: raw byte records <-> typed columns
        # (reference: 'format' = 'json' resolved through the
        # DeserializationFormatFactory SPI)
        from flink_tpu.connectors.formats import resolve_format

        deser, ser = resolve_format(fmt, cols, col_types, opts)
    source = KafkaSource(topic, broker_name=broker_name, bounded=bounded,
                         timestamp_field=wm_field, value_format=deser)
    strategy = source.watermark_strategy(stmt.watermark_delay_ms)
    stream = tenv.env.from_source(source, strategy)
    tenv.create_temporary_view(stmt.name, stream, columns=cols,
                               time_field=wm_field)
    pk = getattr(stmt, "primary_key", None)
    if pk:
        bad = [k for k in pk if k not in cols]
        if bad:
            raise PlanError(
                f"CREATE TABLE {stmt.name}: PRIMARY KEY columns {bad} "
                f"are not table columns {cols}")
    tenv.create_sink_table(
        stmt.name,
        KafkaSink(topic, broker_name=broker_name,
                  partition_by=opts.get("sink.partition-by"),
                  num_partitions=int(opts.get("sink.partitions", "1")),
                  upsert_keys=pk, value_format=ser),
        columns=cols)


def _datagen_factory(tenv, stmt) -> None:
    """'datagen': the deterministic synthetic source as a SQL table
    (reference: the datagen connector)."""
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.table.environment import PlanError

    opts = stmt.options
    cols = [c for c, _ in stmt.columns]
    if len(cols) < 2:
        raise PlanError(
            f"CREATE TABLE {stmt.name}: datagen needs (key_col, "
            "value_col [, ...]) columns")
    source = DataGenSource(
        total_records=int(opts.get("number-of-rows", "10000")),
        num_keys=int(opts.get("number-of-keys", "100")),
        events_per_second_of_eventtime=int(
            opts.get("rows-per-second", "10000")),
        key_field=cols[0], value_field=cols[1],
        seed=int(opts.get("seed", "7")))
    strategy = WatermarkStrategy.for_bounded_out_of_orderness(
        stmt.watermark_delay_ms)
    stream = tenv.env.from_source(source, strategy)
    tenv.create_temporary_view(stmt.name, stream, columns=cols,
                               time_field=stmt.watermark_field)


def _collect_factory(tenv, stmt) -> None:
    """'collect': an in-memory append/changelog sink table for tests and
    interactive use (reference: the 'blackhole'/test sinks)."""
    from flink_tpu.connectors.sinks import CollectSink

    sink = CollectSink()
    sink.supports_changelog = _opt_bool(stmt.options, "changelog", False)
    cols = [c for c, _ in stmt.columns] or None
    tenv.create_sink_table(stmt.name, sink, columns=cols)


def _filesystem_factory(tenv, stmt) -> None:
    """'filesystem': bucketed exactly-once FileSink AND a bounded
    committed-files scan under the same table name (reference: the
    filesystem table connector — readable and writable, partitioned
    directories, 'format' option through the (De)SerializationSchema
    seam). Options:

    - ``path`` (required), ``format`` (default 'json')
    - ``sink.bucket-by``: column name, or ``sink.bucket-datetime``:
      strftime pattern over event time (partitioned directories)
    - ``sink.rolling-policy.max-part-bytes`` / ``.max-part-records`` /
      ``.rollover-interval-ms``
    """
    from flink_tpu.connectors.filesystem import (
        ColumnBucketAssigner,
        DateTimeBucketAssigner,
        FileSink,
        FileSource,
        RollingPolicy,
    )
    from flink_tpu.connectors.formats import resolve_format
    from flink_tpu.table.environment import PlanError

    opts = stmt.options
    path = opts.get("path")
    if not path:
        raise PlanError(f"CREATE TABLE {stmt.name}: filesystem connector "
                        "requires a 'path' option")
    cols = [c for c, _ in stmt.columns]
    col_types = [t for _, t in stmt.columns]
    fmt = opts.get("format", "json")
    deser, ser = resolve_format(fmt, cols, col_types, opts)

    assigner = None
    if opts.get("sink.bucket-by"):
        bucket_col = opts["sink.bucket-by"]
        if bucket_col not in cols:
            raise PlanError(
                f"CREATE TABLE {stmt.name}: sink.bucket-by column "
                f"{bucket_col!r} is not a table column {cols}")
        assigner = ColumnBucketAssigner(bucket_col)
    elif opts.get("sink.bucket-datetime"):
        assigner = DateTimeBucketAssigner(opts["sink.bucket-datetime"])
    policy = RollingPolicy(
        max_part_bytes=int(opts.get(
            "sink.rolling-policy.max-part-bytes", 128 << 20)),
        max_part_records=int(opts.get(
            "sink.rolling-policy.max-part-records", 0)),
        rollover_interval_ms=int(opts.get(
            "sink.rolling-policy.rollover-interval-ms", 0)))
    tenv.create_sink_table(
        stmt.name,
        FileSink(path, cols, fmt=ser, bucket_assigner=assigner,
                 rolling_policy=policy),
        columns=cols)

    wm_field = stmt.watermark_field
    source = FileSource(path, deser, timestamp_field=wm_field)
    from flink_tpu.runtime.watermarks import WatermarkStrategy

    strategy = WatermarkStrategy.for_bounded_out_of_orderness(
        stmt.watermark_delay_ms or 0)
    stream = tenv.env.from_source(source, strategy)
    tenv.create_temporary_view(stmt.name, stream, columns=cols,
                               time_field=wm_field)


register_connector("kafka", _kafka_factory)
register_connector("datagen", _datagen_factory)
register_connector("collect", _collect_factory)
register_connector("filesystem", _filesystem_factory)
