"""SQL AST -> DataStream transformation planner.

reference: the Calcite optimize + translate pipeline
(flink-table-planner/.../delegation/PlannerBase.scala:175 translate,
:412 translateToExecNodeGraph; window agg at
StreamExecWindowAggregate.java:164). The AST first passes through
flink_tpu.table.optimizer (constant folding, filter/join pushdown), then
the supported SQL shapes map 1:1 onto the vectorized operators —
* window TVF + GROUP BY  -> WindowAggOperator (slice-shared device agg)
* plain GROUP BY         -> GroupAggOperator (upsert stream)
* ROW_NUMBER() OVER      -> RankOperator (Top-N)
* JOIN with time bounds  -> IntervalJoinOperator
* JOIN on equality       -> buffered equi-join (unbounded interval join)
* WHERE / projections    -> Filter/Map with vectorized expressions

"Codegen" is JAX tracing of the aggregation kernels; scalar expressions run
as NumPy array ops on the host columns (flink_tpu.table.expressions).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.records import (
    KEY_ID_FIELD,
    ROWKIND_FIELD,
    TIMESTAMP_FIELD,
    RecordBatch,
)
from flink_tpu.datastream.stream import DataStream
from flink_tpu.graph.transformations import Transformation
from flink_tpu.runtime.group_agg import GroupAggOperator
from flink_tpu.runtime.operators import (
    FilterOperator,
    KeyByOperator,
    MapOperator,
)
from flink_tpu.runtime.rank_operator import RankOperator
from flink_tpu.table import sql_parser as ast
from flink_tpu.table.expressions import (
    AggCall,
    Between,
    BinaryOp,
    Column,
    Expr,
    Literal,
    OverAgg,
    OverCall,
    SelectItem,
    Star,
)
from flink_tpu.windowing.aggregates import (
    AggregateFunction,
    AvgAggregate,
    CountAggregate,
    MaxAggregate,
    MinAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.assigners import (
    CumulativeEventTimeWindows,
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.windowing.windower import WINDOW_END_FIELD, WINDOW_START_FIELD

GROUP_KEY_FIELD = "__group_key__"
_WINDOW_COLS = (WINDOW_START_FIELD, WINDOW_END_FIELD, "window_time")

_UNBOUNDED = 1 << 60


class PlanError(ValueError):
    pass


@dataclasses.dataclass
class PlannedTable:
    """A planned relational node: a stream plus its visible column names."""

    stream: DataStream
    columns: List[str]
    alias: Optional[str] = None
    #: which visible column is the event-time attribute (maps to __ts__)
    time_field: Optional[str] = None
    #: non-None marks an upsert (changelog) stream keyed by these columns
    upsert_keys: Optional[List[str]] = None
    #: ORDER BY / LIMIT applied at materialization time (bounded results)
    sort_spec: Optional[List[Tuple[Expr, bool]]] = None
    limit: Optional[int] = None


class Planner:
    def __init__(self, t_env):
        self.t_env = t_env
        self.env = t_env.env

    # ------------------------------------------------------------ entry

    def plan_select(self, stmt) -> PlannedTable:
        if isinstance(stmt, ast.UnionAll):
            return self._plan_union(stmt)
        window = None
        if isinstance(stmt.table, ast.WindowTVF):
            window = stmt.table
            source = self._plan_table_ref(window.table)
            source.alias = window.alias or source.alias
        else:
            source = self._plan_table_ref(stmt.table)

        aliases = self._collect_aliases(stmt.table)
        resolve = lambda e: self._resolve(e, source.columns, aliases)  # noqa: E731

        where = resolve(stmt.where) if stmt.where is not None else None
        items = self._expand_star(
            [SelectItem(resolve(i.expr), i.alias) for i in stmt.items],
            source, window)
        group_by = [resolve(g) for g in stmt.group_by]
        having = resolve(stmt.having) if stmt.having is not None else None

        stream = source.stream
        if where is not None:
            stream = stream.filter(lambda b, e=where: e.eval(b).astype(bool),
                                   name="sql_where")

        has_aggs = bool(group_by) or any(i.expr.aggregates() for i in items) \
            or stmt.distinct
        over_calls = [i for i in items if isinstance(i.expr, OverCall)]
        over_aggs = [i for i in items if isinstance(i.expr, OverAgg)]
        for i in items:
            if isinstance(i.expr, (OverCall, OverAgg)):
                continue
            nested = [n for n in i.expr.walk()
                      if isinstance(n, (OverCall, OverAgg))]
            if nested:
                raise PlanError(
                    "an OVER window must be a top-level SELECT item "
                    f"(found one nested inside {i.name!r}); compute it "
                    "in a subquery first")

        if over_aggs:
            if has_aggs or over_calls:
                raise PlanError(
                    "OVER aggregates cannot mix with GROUP BY or "
                    "ROW_NUMBER in one SELECT; use a subquery")
            return self._plan_over_agg(stream, source, items, over_aggs,
                                       stmt)
        if over_calls:
            if has_aggs:
                raise PlanError("OVER and GROUP BY in one SELECT "
                                "are not supported; use a subquery")
            return self._plan_over(stream, source, items, over_calls, stmt)
        if has_aggs:
            return self._plan_aggregate(stream, source, items, group_by,
                                        having, window, stmt)
        if window is not None:
            raise PlanError("a window TVF requires GROUP BY window_start, "
                            "window_end")
        return self._plan_projection(stream, source, items, stmt)

    def _plan_union(self, stmt: "ast.UnionAll") -> PlannedTable:
        """UNION ALL: plan every branch, require identical output columns,
        merge the streams (reference: StreamExecUnion — a plain stream
        merge, no exchange)."""
        planned = [self.plan_select(s) for s in stmt.selects]
        cols = planned[0].columns
        for p in planned:
            if p.columns != cols:
                raise PlanError(
                    "UNION ALL branches must produce identical columns; "
                    f"got {cols} vs {p.columns}")
            if p.upsert_keys is not None:
                # merging changelog streams would alias per-branch keys:
                # downstream upsert materialization keeps one row per key
                # ACROSS branches, silently dropping the other branch's
                raise PlanError(
                    "UNION ALL over an updating (changelog) branch is "
                    "not supported — materialize the aggregates first "
                    "(e.g. windowed aggregation) or union the raw inputs")
        # event-time agreement cannot be decided here (projections
        # legitimately drop the time-field marker while the timestamp
        # column rides along) — the union operator's runtime guard
        # (strict for SQL unions) names the cause instead
        stream = planned[0].stream.union(
            *[p.stream for p in planned[1:]],
            _require_consistent_time=True) if len(planned) > 1 \
            else planned[0].stream
        out = PlannedTable(stream, list(cols), None, planned[0].time_field)
        return self._apply_order_limit(out, stmt)

    # ------------------------------------------------------- FROM clause

    def _plan_table_ref(self, ref: ast.TableRef) -> PlannedTable:
        from flink_tpu.table.fluent import _InlineTable

        if isinstance(ref, _InlineTable):
            # the fluent API's FROM clause: a live Table object instead of
            # a catalog name (reference: Table API queries never register)
            t = ref.table
            if t.sort_spec is not None or t.limit is not None:
                # ORDER BY / LIMIT are materialization-time decorations in
                # this engine; further relational ops over them would
                # silently ignore the sort/limit — fail instead
                raise PlanError(
                    "order_by()/fetch() are terminal operations — apply "
                    "them AFTER the other relational operations (their "
                    "sort/limit applies when the table materializes)")
            return PlannedTable(t.stream, list(t.columns), ref.alias,
                                t.time_field, t.upsert_keys)
        if isinstance(ref, ast.NamedTable):
            t = self.t_env.lookup(ref.name)
            if t.sort_spec is not None or t.limit is not None:
                # a view/table carrying ORDER BY/LIMIT: those are
                # materialization-time decorations an enclosing query
                # would silently discard — same contract as subqueries
                raise PlanError(
                    f"table/view {ref.name!r} carries ORDER BY / LIMIT, "
                    "which only applies when it materializes directly — "
                    "query the underlying data and apply the sort/limit "
                    "in the outermost query")
            return PlannedTable(t.stream, list(t.columns), ref.alias,
                                t.time_field, t.upsert_keys)
        if isinstance(ref, ast.SubQuery):
            inner = self.plan_select(ref.query)
            if inner.sort_spec is not None or inner.limit is not None:
                # ORDER BY/LIMIT are materialization-time; an enclosing
                # query would silently ignore them (same contract as the
                # fluent API's terminal order_by/fetch)
                raise PlanError(
                    "ORDER BY / LIMIT inside a subquery is not supported "
                    "— apply them in the outermost query")
            inner.alias = ref.alias
            return inner
        if isinstance(ref, ast.WindowTVF):
            raise PlanError("window TVF only supported directly in FROM of "
                            "an aggregating SELECT")
        if isinstance(ref, ast.MLPredictTVF):
            return self._plan_ml_predict(ref)
        if isinstance(ref, ast.Join):
            return self._plan_join(ref)
        if isinstance(ref, ast.MatchRecognize):
            return self._plan_match_recognize(ref)
        raise PlanError(f"unsupported table ref {ref!r}")

    # --------------------------------------------------- MATCH_RECOGNIZE

    def _plan_match_recognize(self, mr: "ast.MatchRecognize"
                              ) -> PlannedTable:
        """MATCH_RECOGNIZE -> the CEP engine (reference: StreamExecMatch
        compiles the row pattern onto flink-cep's NFA). Row-pattern
        semantics: variables bind CONSECUTIVE rows of the partition in
        rowtime order (strict contiguity; loops are consecutive), and
        SQL quantifiers are greedy unless marked reluctant with '?'."""
        from flink_tpu.cep.operator import CepOperator
        from flink_tpu.cep.pattern import (
            AfterMatchSkipStrategy,
            Pattern,
        )

        source = self._plan_table_ref(mr.table)
        if source.upsert_keys is not None:
            raise PlanError(
                "MATCH_RECOGNIZE over an updating (changelog) input is "
                "not supported — inputs must be insert-only")
        if source.time_field is None:
            raise PlanError(
                "MATCH_RECOGNIZE requires the table to declare an "
                "event-time column (WATERMARK FOR ...)")
        if mr.order_by is None or mr.order_by != source.time_field:
            raise PlanError(
                "MATCH_RECOGNIZE must ORDER BY the table's event-time "
                f"column ({source.time_field!r}); got {mr.order_by!r}")
        if len(mr.partition_by) != 1:
            raise PlanError(
                "MATCH_RECOGNIZE supports PARTITION BY exactly one "
                "column")
        key_col = mr.partition_by[0]
        if key_col not in source.columns:
            raise PlanError(
                f"PARTITION BY column {key_col!r} is not a column of "
                f"the input ({source.columns})")
        if not mr.pattern:
            raise PlanError("PATTERN () is empty")
        var_names = [v for v, _, _, _ in mr.pattern]
        if len(set(var_names)) != len(var_names):
            raise PlanError(
                f"duplicate pattern variables: {var_names}")
        unknown = [v for v in mr.define if v not in var_names]
        if unknown:
            raise PlanError(
                f"DEFINE names unknown pattern variables: {unknown}")
        for func, var, col, alias in mr.measures:
            if var not in var_names:
                raise PlanError(
                    f"measure references unknown pattern variable "
                    f"{var!r}")
            if col not in source.columns:
                raise PlanError(
                    f"measure column {col!r} is not an input column")

        pat = None
        for var, mn, mx, greedy in mr.pattern:
            if pat is None:
                pat = Pattern.begin(var)
            else:
                pat = pat.next(var)
            if (mn, mx) != (1, 1):
                if mx is None:
                    pat = pat.times_or_more(mn)
                else:
                    pat = pat.times(mn, mx)
                # row-pattern loops bind consecutive rows
                pat = pat.consecutive()
                if greedy and (mx is None or mx > 1):
                    pat = pat.greedy()
            cond = mr.define.get(var)
            if cond is not None:
                pat = self._compile_define(pat, cond, var, var_names)
        if mr.within_ms is not None:
            pat = pat.within(mr.within_ms)
        if mr.after_match == "PAST_LAST_ROW":
            pat = pat.with_skip_strategy(
                AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)

        measures = list(mr.measures)

        def select(key_value, match, events_by_stage,
                   _measures=tuple(measures), _key_col=key_col):
            row = {_key_col: key_value}
            for func, var, col, alias in _measures:
                evs = events_by_stage.get(var, [])
                vals = [e[col] for e in evs]
                if not vals:
                    row[alias] = (0 if func == "COUNT" else np.nan)
                elif func == "FIRST":
                    row[alias] = vals[0]
                elif func == "LAST":
                    row[alias] = vals[-1]
                elif func == "COUNT":
                    row[alias] = len(vals)
                elif func == "SUM":
                    row[alias] = float(np.sum(vals))
                elif func == "AVG":
                    row[alias] = float(np.mean(vals))
                elif func == "MIN":
                    row[alias] = min(vals)
                else:
                    row[alias] = max(vals)
            return row

        keyed = source.stream.key_by(key_col)
        pat = pat.validate()
        # cep.mode=device routes the row pattern onto the mesh NFA
        # engine (one compiled advance per fire, matches queryable via
        # the replica plane). Eligibility is checked HERE so the plan
        # explains itself: an ineligible pattern plans the host
        # operator with the loud fallback counter, not a job failure.
        from flink_tpu.core.config import DeploymentOptions

        cep_mode = self.env.config.get(DeploymentOptions.CEP_MODE)
        if cep_mode == "device":
            from flink_tpu.cep.kernels import UnsupportedCepPattern
            from flink_tpu.cep.kernels import compile_device_pattern
            from flink_tpu.cep.mesh_engine import record_host_fallback

            try:
                compile_device_pattern(pat)
            except UnsupportedCepPattern as e:
                record_host_fallback(
                    f"MATCH_RECOGNIZE {mr.alias or ''}: {e}")
                cep_mode = "host"
        if cep_mode == "device":
            from flink_tpu.cep.operators import MeshCepOperator

            factory = (lambda pat=pat, key_col=key_col, sel=select:
                       MeshCepOperator(pat, key_col, select=sel))
        else:
            factory = (lambda pat=pat, key_col=key_col, sel=select:
                       CepOperator(pat, key_col, select=sel))
        t = Transformation(
            name="sql_match_recognize", kind="one_input",
            operator_factory=factory,
            inputs=[keyed.transformation], keyed=True, key_field=key_col)
        out_cols = [key_col] + [alias for _, _, _, alias in measures]
        return PlannedTable(DataStream(self.env, t), out_cols, mr.alias,
                            None)

    def _compile_define(self, pat, expr: Expr, var: str,
                        var_names: List[str]):
        """A DEFINE condition: references to the variable's OWN columns
        vectorize (one mask per batch); references to OTHER variables'
        events (B.price < A.price) become an iterative condition reading
        the partial match (reference: MATCH_RECOGNIZE DEFINE lowering to
        IterativeCondition)."""
        cross = [n for n in expr.walk()
                 if isinstance(n, Column) and n.table
                 and n.table.upper() in var_names
                 and n.table.upper() != var]
        own_refs = {n: Column(n.name) for n in expr.walk()
                    if isinstance(n, Column) and n.table
                    and n.table.upper() == var}
        if not cross:
            cond_expr = expr.rewrite(own_refs) if own_refs else expr

            def vcond(b, e=cond_expr):
                return np.asarray(e.eval(b), dtype=bool)

            return pat.where(vcond)

        def icond(event_row, ctx, e=expr, cross=tuple(cross),
                  own=dict(own_refs)):
            mapping = dict(own)
            for r in cross:
                evs = ctx.events_for(r.table.upper())
                if not evs:
                    # LAST(X.col) over no events is NULL; a NULL
                    # comparison is not satisfied (SQL three-valued
                    # logic collapses to false here)
                    return False
                mapping[r] = Literal(evs[-1][r.name])
            e2 = e.rewrite(mapping)
            batch = RecordBatch.from_pydict(
                {k: np.asarray([v]) for k, v in event_row.items()
                 if not k.startswith("__")})
            return bool(np.asarray(e2.eval(batch))[0])

        return pat.where_iterative(icond)

    def _plan_ml_predict(self, ref: "ast.MLPredictTVF") -> PlannedTable:
        """ML_PREDICT(TABLE t, MODEL m, DESCRIPTOR(...)) — one batched
        inference per micro-batch appending the model's output columns
        (reference: MLPredictRunner invoked from SQL; flink-models)."""
        from flink_tpu.ml.operators import MLPredictOperator

        inner = self._plan_table_ref(ref.table)
        if inner.upsert_keys is not None:
            raise PlanError("ML_PREDICT over an updating (changelog) "
                            "input is not supported")
        model = self.t_env.models.get(ref.model)
        missing = [f for f in ref.fields if f not in inner.columns]
        if missing:
            raise PlanError(
                f"ML_PREDICT descriptor columns {missing} not in input "
                f"columns {inner.columns}")
        if len(ref.fields) != len(model.input_names):
            raise PlanError(
                f"model {ref.model!r} expects "
                f"{len(model.input_names)} inputs "
                f"{tuple(model.input_names)}, the DESCRIPTOR names "
                f"{len(ref.fields)}: {tuple(ref.fields)}")
        t = Transformation(
            name=f"ml_predict({ref.model})", kind="one_input",
            operator_factory=lambda: MLPredictOperator(
                model, input_fields=ref.fields),
            inputs=[inner.stream.transformation])
        out_cols = list(inner.columns) + [
            n for n in model.output_names if n not in inner.columns]
        return PlannedTable(DataStream(self.env, t), out_cols,
                            ref.alias or inner.alias, inner.time_field)

    def _collect_aliases(self, ref: ast.TableRef,
                         side: str = "") -> Dict[str, str]:
        """alias -> join-suffix ('' when unambiguous, '_l'/'_r' in a join)."""
        out: Dict[str, str] = {}
        if isinstance(ref, ast.Join):
            out.update(self._collect_aliases(ref.left, "_l"))
            out.update(self._collect_aliases(ref.right, "_r"))
            return out
        if isinstance(ref, ast.MLPredictTVF):
            # qualified columns keep resolving by the inner table's name
            # (same treatment as the WindowTVF branch below)
            out = self._collect_aliases(ref.table, side)
            if ref.alias is not None:
                out[ref.alias] = side
            return out
        alias = getattr(ref, "alias", None)
        if alias is None and isinstance(ref, ast.NamedTable):
            alias = ref.name
        if isinstance(ref, ast.WindowTVF):
            inner = ref.table
            if isinstance(inner, ast.NamedTable):
                out[alias or inner.name] = side
                if alias is None and inner.alias:
                    out[inner.alias] = side
                out[inner.name] = side
                return out
        if alias is not None:
            out[alias] = side
        return out

    # --------------------------------------------------------- resolution

    def _resolve(self, expr: Expr, columns: Sequence[str],
                 aliases: Dict[str, str]) -> Expr:
        """Strip table qualifiers, mapping to suffixed columns after joins."""
        if isinstance(expr, Column):
            if expr.table is None:
                return expr
            suffix = aliases.get(expr.table, "")
            if suffix and (expr.name + suffix) in columns:
                return Column(expr.name + suffix)
            return Column(expr.name)
        if isinstance(expr, OverCall):
            return OverCall(
                expr.func,
                tuple(self._resolve(e, columns, aliases)
                      for e in expr.partition_by),
                tuple((self._resolve(e, columns, aliases), d)
                      for e, d in expr.order_by))
        if isinstance(expr, OverAgg):
            return OverAgg(
                expr.func,
                self._resolve(expr.arg, columns, aliases)
                if expr.arg is not None else None,
                tuple(self._resolve(e, columns, aliases)
                      for e in expr.partition_by),
                tuple((self._resolve(e, columns, aliases), d)
                      for e, d in expr.order_by),
                mode=expr.mode, preceding=expr.preceding)
        mapping = {
            node: self._resolve(node, columns, aliases)
            for node in expr.walk()
            if isinstance(node, Column) and node.table is not None
        }
        return expr.rewrite(mapping) if mapping else expr

    def _expand_star(self, items: List[SelectItem], source: PlannedTable,
                     window) -> List[SelectItem]:
        out: List[SelectItem] = []
        for i in items:
            if isinstance(i.expr, Star):
                for c in source.columns:
                    out.append(SelectItem(Column(c)))
                if window is not None:
                    out.append(SelectItem(Column(WINDOW_START_FIELD)))
                    out.append(SelectItem(Column(WINDOW_END_FIELD)))
            else:
                out.append(i)
        return out

    # ------------------------------------------------------- projections

    def _plan_projection(self, stream: DataStream, source: PlannedTable,
                         items: List[SelectItem],
                         stmt: ast.SelectStmt) -> PlannedTable:
        names = [i.name for i in items]
        exprs = [i.expr for i in items]

        def project(batch: RecordBatch, exprs=exprs, names=names):
            cols = {n: np.asarray(e.eval(batch))
                    for n, e in zip(names, exprs)}
            if batch.has_timestamps:
                cols[TIMESTAMP_FIELD] = batch.timestamps
            if ROWKIND_FIELD in batch.columns:
                # changelog kinds ride through projections untouched
                cols[ROWKIND_FIELD] = batch[ROWKIND_FIELD]
            return RecordBatch(cols)

        out = stream.map(project, name="sql_project")
        return self._finish(out, names, source, stmt)

    # -------------------------------------------------------- aggregation

    def _plan_aggregate(self, stream: DataStream, source: PlannedTable,
                        items: List[SelectItem], group_by: List[Expr],
                        having: Optional[Expr], window: Optional[ast.WindowTVF],
                        stmt: ast.SelectStmt) -> PlannedTable:
        updating_input = source.upsert_keys is not None
        if updating_input and window is not None:
            raise PlanError(
                "event-time window aggregate over an updating (changelog) "
                "input is not supported — window state cannot retract "
                "(reference: StreamPhysicalWindowAggregate requires "
                "insert-only input)")
        if stmt.distinct and not any(i.expr.aggregates() for i in items) \
                and not group_by:
            group_by = [i.expr for i in items]

        # split group keys into window bookkeeping columns vs data keys
        key_exprs: List[Expr] = []
        for g in group_by:
            if isinstance(g, Column) and g.name in _WINDOW_COLS:
                if window is None and g.name not in source.columns:
                    raise PlanError(f"GROUP BY {g.name} without a window TVF")
                if window is not None:
                    continue  # implicit in the window agg output
            key_exprs.append(g)

        # aggregate calls, deduped structurally
        agg_calls: List[AggCall] = []
        for i in items:
            for a in i.expr.aggregates():
                if a not in agg_calls:
                    agg_calls.append(a)
        if having is not None:
            for a in having.aggregates():
                if a not in agg_calls:
                    agg_calls.append(a)
        if not agg_calls:
            agg_calls.append(AggCall("COUNT", None))  # pure DISTINCT

        # materialize computed key / agg-input columns
        pre_cols: Dict[str, Expr] = {}
        key_fields: List[str] = []
        for ki, g in enumerate(key_exprs):
            if isinstance(g, Column):
                key_fields.append(g.name)
            else:
                name = f"__key_{ki}__"
                pre_cols[name] = g
                key_fields.append(name)
        agg_fns: List[AggregateFunction] = []
        agg_out_names: List[str] = []
        for ai, a in enumerate(agg_calls):
            if a.distinct:
                raise PlanError("DISTINCT aggregates are not supported yet")
            out_name = f"__agg_{ai}__"
            agg_out_names.append(out_name)
            if a.func == "COUNT":
                agg_fns.append(CountAggregate(output=out_name))
                continue
            if isinstance(a.arg, Column):
                field = a.arg.name
            else:
                field = f"__agg_in_{ai}__"
                pre_cols[field] = a.arg
            cls = {"SUM": SumAggregate, "MIN": MinAggregate,
                   "MAX": MaxAggregate, "AVG": AvgAggregate}[a.func]
            if cls is AvgAggregate:
                agg_fns.append(AvgAggregate(field, output=out_name))
            else:
                agg_fns.append(cls(field, output=out_name))

        if pre_cols:
            def add_cols(batch, pre_cols=pre_cols):
                for n, e in pre_cols.items():
                    batch = batch.with_column(n, np.asarray(e.eval(batch)))
                return batch

            stream = stream.map(add_cols, name="sql_pre_project")

        # composite / missing key handling
        if len(key_fields) == 0:
            const_key = "__global__"

            def add_const(batch, name=const_key):
                return batch.with_column(
                    name, np.zeros(len(batch), dtype=np.int64))

            stream = stream.map(add_const, name="sql_global_key")
            key_field = const_key
        elif len(key_fields) == 1:
            key_field = key_fields[0]
        else:
            key_field = GROUP_KEY_FIELD

            def add_tuple_key(batch, fields=tuple(key_fields)):
                vals = list(zip(*[batch[f].tolist() for f in fields]))
                arr = np.empty(len(batch), dtype=object)
                arr[:] = vals
                return batch.with_column(GROUP_KEY_FIELD, arr)

            stream = stream.map(add_tuple_key, name="sql_composite_key")

        keyed = stream.key_by(key_field)
        multi = MultiAggregate(agg_fns)
        if updating_input and not multi.retractable:
            raise PlanError(
                "MAX/MIN over an updating (changelog) input requires "
                "retractable accumulators, which MAX/MIN are not "
                "(reference: MaxWithRetractAggFunction keeps a sorted "
                "multiset; use an append-only input or COUNT/SUM/AVG)")
        upsert_keys: Optional[List[str]] = None
        if window is not None:
            assigner = _window_assigner(window)
            agged = keyed.window(assigner).aggregate(
                multi, name=f"sql_{window.kind.lower()}_agg")
        else:
            capacity = self.env.state_slot_capacity
            from flink_tpu.core.config import ExecutionModeOptions

            # batch mode: one changelog row per group at end-of-input
            # instead of per-micro-batch upsert churn (reference: batch
            # mode runs GROUP BY as a bounded aggregate, emitting finals)
            batch_final = self.env.config.get(
                ExecutionModeOptions.RUNTIME_MODE) == "batch"
            from flink_tpu.core.config import StateOptions

            # TTL applies to STREAMING only — in batch mode emission is
            # deferred to end-of-input, and a mid-ingest sweep would
            # silently delete groups from the final result (the
            # reference's table.exec.state.ttl is likewise stream-only)
            ttl = None if batch_final else (self.env.config.get(
                StateOptions.TABLE_EXEC_STATE_TTL) or None)
            t = Transformation(
                name="sql_group_agg", kind="one_input",
                operator_factory=lambda: GroupAggOperator(
                    multi, key_field, capacity=capacity,
                    emit_on_watermark_only=batch_final,
                    ttl_ms=ttl),
                inputs=[keyed.transformation], keyed=True,
                key_field=key_field)
            agged = DataStream(self.env, t)
            upsert_keys = list(key_fields) or [const_key]

        # split composite tuple key back into its columns
        post = agged
        if key_field == GROUP_KEY_FIELD:
            def split_key(batch, fields=tuple(key_fields)):
                tuples = batch[GROUP_KEY_FIELD]
                for j, f in enumerate(fields):
                    batch = batch.with_column(
                        f, np.array([t[j] for t in tuples], dtype=object))
                return batch.drop(GROUP_KEY_FIELD)

            post = post.map(split_key, name="sql_split_key")
            if upsert_keys is not None:
                upsert_keys = list(key_fields)

        if having is not None:
            hav = self._sub_aggs(having, agg_calls, agg_out_names)
            post = post.filter(
                lambda b, e=hav: np.asarray(e.eval(b)).astype(bool),
                name="sql_having")

        # final projection over (keys + window cols + agg results)
        names, exprs = [], []
        for i in items:
            names.append(self._agg_item_name(i))
            exprs.append(self._sub_aggs(i.expr, agg_calls, agg_out_names))

        def project(batch, exprs=tuple(exprs), names=tuple(names)):
            cols = {n: np.asarray(e.eval(batch))
                    for n, e in zip(names, exprs)}
            if batch.has_timestamps:
                cols[TIMESTAMP_FIELD] = batch.timestamps
            if ROWKIND_FIELD in batch.columns:
                # the group agg's changelog kinds survive the projection so
                # downstream consumers (outer aggregates, upsert
                # materialization) see retractions
                cols[ROWKIND_FIELD] = batch[ROWKIND_FIELD]
            return RecordBatch(cols)

        out = post.map(project, name="sql_agg_project")
        planned = PlannedTable(out, list(names), source.alias,
                               time_field=WINDOW_END_FIELD
                               if window is not None
                               and WINDOW_END_FIELD in names else None,
                               upsert_keys=None)
        if upsert_keys is not None:
            # project the upsert keys through the select list; a global
            # aggregate (no keys in the output) dedupes to the last row
            planned.upsert_keys = [n for n, e in zip(names, exprs)
                                   if isinstance(e, Column)
                                   and e.name in upsert_keys]
        return self._apply_order_limit(planned, stmt)

    @staticmethod
    def _agg_item_name(item: SelectItem) -> str:
        if item.alias:
            return item.alias
        return item.expr.output_name()

    @staticmethod
    def _sub_aggs(expr: Expr, agg_calls: List[AggCall],
                  out_names: List[str]) -> Expr:
        mapping = {a: Column(n) for a, n in zip(agg_calls, out_names)}
        return expr.rewrite(mapping)

    # ------------------------------------------------------------- Top-N

    def _plan_over(self, stream: DataStream, source: PlannedTable,
                   items: List[SelectItem], over_items: List[SelectItem],
                   stmt: ast.SelectStmt) -> PlannedTable:
        if len(over_items) != 1:
            raise PlanError("exactly one OVER call per SELECT is supported")
        if source.upsert_keys is not None:
            raise PlanError(
                "OVER/Top-N over an updating (changelog) input is not "
                "supported yet — rank inputs must be insert-only "
                "(reference: AppendOnlyTopNFunction vs RetractableTopN)")
        item = over_items[0]
        over: OverCall = item.expr
        rank_name = item.alias or over.output_name()
        t = Transformation(
            name="sql_rank", kind="one_input",
            operator_factory=lambda: RankOperator(
                over.partition_by, over.order_by, rank_field=rank_name,
                rank_kind=over.func),
            inputs=[stream.transformation])
        ranked = DataStream(self.env, t)

        names, exprs = [], []
        for i in items:
            if i is item:
                names.append(rank_name)
                exprs.append(Column(rank_name))
            else:
                names.append(i.name)
                exprs.append(i.expr)

        def project(batch, exprs=tuple(exprs), names=tuple(names)):
            cols = {n: np.asarray(e.eval(batch))
                    for n, e in zip(names, exprs)}
            if batch.has_timestamps:
                cols[TIMESTAMP_FIELD] = batch.timestamps
            return RecordBatch(cols)

        out = ranked.map(project, name="sql_rank_project")
        return self._finish(out, names, source, stmt)

    # ----------------------------------------------------- OVER aggregates

    def _plan_over_agg(self, stream: DataStream, source: PlannedTable,
                       items: List[SelectItem],
                       over_items: List[SelectItem],
                       stmt: ast.SelectStmt) -> PlannedTable:
        """agg(x) OVER (PARTITION BY k ORDER BY rowtime frame) —
        reference: StreamExecOverAggregate. Every OVER call in one
        SELECT must share one window spec (the reference's
        single-over-window-per-operator restriction)."""
        from flink_tpu.runtime.over_agg import OverAggOperator

        if source.upsert_keys is not None:
            raise PlanError(
                "OVER aggregation over an updating (changelog) input is "
                "not supported — inputs must be insert-only")
        first: OverAgg = over_items[0].expr
        for i in over_items[1:]:
            o: OverAgg = i.expr
            if (o.partition_by, o.order_by, o.mode, o.preceding) != (
                    first.partition_by, first.order_by, first.mode,
                    first.preceding):
                raise PlanError(
                    "all OVER aggregates in one SELECT must share the "
                    "same window (PARTITION BY / ORDER BY / frame)")
        if len(first.partition_by) != 1 or not isinstance(
                first.partition_by[0], Column):
            raise PlanError(
                "OVER requires PARTITION BY exactly one column")
        key_col = first.partition_by[0].name
        if len(first.order_by) != 1 or first.order_by[0][1]:
            raise PlanError(
                "OVER requires ORDER BY the event-time column ASC")
        order_col = first.order_by[0][0]
        if source.time_field is None:
            # the operator orders frames by the rows' event time — with
            # no declared time attribute an arbitrary ORDER BY column
            # would be silently ignored (reference: streaming OVER
            # requires a time attribute order)
            raise PlanError(
                "OVER requires the table to declare an event-time "
                "column (WATERMARK FOR ...) and ORDER BY it")
        if not isinstance(order_col, Column) or \
                order_col.name != source.time_field:
            raise PlanError(
                "OVER must ORDER BY the table's event-time column "
                f"({source.time_field!r}); got "
                f"{order_col.output_name()!r} (reference: streaming OVER "
                "windows are rowtime-ordered)")

        # materialize non-column arguments as temp columns first; the
        # operator writes INTERNAL output names so a user alias can
        # never clobber a source column another select item still reads
        specs = []
        out_names: Dict[int, str] = {}
        pre_cols: List[Tuple[str, Expr]] = []
        for j, item in enumerate(over_items):
            o: OverAgg = item.expr
            internal = f"__over_out_{j}__"
            out_names[id(item)] = internal
            if o.arg is None:
                specs.append((o.func, None, internal))
            elif isinstance(o.arg, Column):
                specs.append((o.func, o.arg.name, internal))
            else:
                tmp = f"__over_arg_{j}__"
                pre_cols.append((tmp, o.arg))
                specs.append((o.func, tmp, internal))
        if pre_cols:
            def add_args(batch, pre=tuple(pre_cols)):
                for name, e in pre:
                    batch = batch.with_column(
                        name, np.asarray(e.eval(batch)))
                return batch

            stream = stream.map(add_args, name="sql_over_args")
        mode, preceding = first.mode, first.preceding
        from flink_tpu.core.config import StateOptions

        engine = self.env.config.get(StateOptions.TABLE_EXEC_OVER_ENGINE)
        from flink_tpu.runtime.over_device import (
            DeviceOverAggOperator, device_supported)

        if engine not in ("auto", "device", "host"):
            raise PlanError(
                f"table.exec.over.engine must be auto/device/host, got "
                f"{engine!r}")

        def _x64() -> bool:
            import jax

            return bool(jax.config.jax_enable_x64)

        # auto only picks the device engine when it computes in f64
        # (JAX x64 on) — silently downgrading SQL DOUBLE aggregates to
        # f32 needs an explicit engine=device opt-in
        use_device = (engine == "device"
                      or (engine == "auto" and device_supported(
                          specs, mode, preceding) and _x64()))
        if engine == "device" and not device_supported(
                specs, mode, preceding):
            raise PlanError(
                "table.exec.over.engine=device: bounded RANGE MIN/MAX "
                "frames have no device form — use engine=host or auto")
        op_cls = DeviceOverAggOperator if use_device else OverAggOperator
        t = Transformation(
            name="sql_over_agg", kind="one_input",
            operator_factory=lambda key_col=key_col, specs=tuple(specs),
            mode=mode, preceding=preceding, op_cls=op_cls: op_cls(
                key_col, list(specs), mode=mode, preceding=preceding),
            inputs=[stream.key_by(key_col).transformation])
        over_stream = DataStream(self.env, t)

        names, exprs = [], []
        for i in items:
            if i in over_items:
                names.append(i.alias or i.expr.output_name())
                exprs.append(Column(out_names[id(i)]))
            else:
                names.append(i.name)
                exprs.append(i.expr)

        def project(batch, exprs=tuple(exprs), names=tuple(names)):
            cols = {n: np.asarray(e.eval(batch))
                    for n, e in zip(names, exprs)}
            if batch.has_timestamps:
                cols[TIMESTAMP_FIELD] = batch.timestamps
            return RecordBatch(cols)

        out = over_stream.map(project, name="sql_over_project")
        return self._finish(out, names, source, stmt)

    # --------------------------------------------------------------- joins

    def _plan_join(self, join: ast.Join) -> PlannedTable:
        if join.temporal is not None:
            return self._plan_temporal_join(join)
        if join.kind not in ("INNER", "LEFT"):
            raise PlanError(f"{join.kind} JOIN is not supported yet")
        left = self._plan_table_ref(join.left)
        right = self._plan_table_ref(join.right)
        if left.upsert_keys is not None or right.upsert_keys is not None:
            raise PlanError(
                "JOIN over an updating (changelog) input is not supported "
                "yet — join inputs must be insert-only")
        l_aliases = self._collect_aliases(join.left)
        r_aliases = self._collect_aliases(join.right)

        conjuncts = _split_conjuncts(join.condition)
        equi: List[Tuple[Expr, Expr]] = []
        time_bounds: Optional[Tuple[int, int]] = None
        residual: List[Expr] = []
        for c in conjuncts:
            pair = self._match_equi(c, left, right, l_aliases, r_aliases)
            if pair is not None:
                equi.append(pair)
                continue
            tb = self._match_time_bound(c, left, right, l_aliases, r_aliases)
            if tb is not None:
                if time_bounds is not None:
                    lo = max(time_bounds[0], tb[0])
                    hi = min(time_bounds[1], tb[1])
                    time_bounds = (lo, hi)
                else:
                    time_bounds = tb
                continue
            residual.append(c)
        if not equi:
            raise PlanError("JOIN requires at least one equality predicate")
        left_outer = join.kind == "LEFT"
        if left_outer and residual:
            # a residual applied as a post-filter would DROP null-padded
            # rows instead of null-extending when the predicate fails on
            # a matched pair — reject rather than silently change LEFT
            # semantics (reference: non-equi conditions are part of the
            # join for outer joins)
            raise PlanError(
                "LEFT JOIN supports only equality and event-time-bound "
                "conditions; move other predicates to WHERE (changing "
                "the null-extension semantics) or use INNER JOIN")
        if left_outer and time_bounds is None:
            raise PlanError(
                "streaming LEFT JOIN requires event-time bounds (an "
                "interval join) so expiry is decidable — add a BETWEEN "
                "over the two rowtimes")

        lower, upper = time_bounds if time_bounds is not None \
            else (-_UNBOUNDED, _UNBOUNDED)
        from flink_tpu.runtime.join_operators import IntervalJoinOperator

        # the padded-row schema must match _merge_columns' matched-row
        # schema exactly, including the synthetic join-key column both
        # sides carry after _key_for_join
        pad_cols = tuple(right.columns) + (GROUP_KEY_FIELD,)
        return self._lower_keyed_join(
            left, right, l_aliases, r_aliases, equi, residual,
            lambda pad_cols=pad_cols: IntervalJoinOperator(
                lower, upper, suffixes=("_l", "_r"),
                left_outer=left_outer,
                right_columns=list(pad_cols)),
            "sql_join")

    def _plan_lookup_join(self, join: ast.Join) -> PlannedTable:
        """JOIN dim FOR SYSTEM_TIME AS OF o.rowtime ON o.k = dim.k where
        ``dim`` is a registered lookup table — the enrichment pattern
        (reference: StreamExecLookupJoin -> LookupJoinRunner; the
        reference's AS OF proctime instant maps to lookup-at-arrival
        here, with the left rowtime column naming the stream side)."""
        from flink_tpu.connectors.lookup import LookupJoinOperator

        fn, r_columns, cache_size, cache_ttl_ms = \
            self.t_env._lookup_tables[join.right.name]
        left = self._plan_table_ref(join.left)
        if left.upsert_keys is not None:
            raise PlanError(
                "lookup join over an updating (changelog) input is not "
                "supported — the stream side must be insert-only")
        l_aliases = self._collect_aliases(join.left)
        r_alias = join.right.alias or join.right.name
        left_outer = join.kind == "LEFT"
        # the AS OF instant must be the stream side's time attribute
        # (the reference requires a proctime attribute; here the left
        # rowtime column names the lookup-at-arrival instant)
        as_of = self._strip(join.temporal, left, l_aliases)
        if left.time_field is None or not isinstance(as_of, Column) \
                or as_of.name != left.time_field:
            raise PlanError(
                "lookup join FOR SYSTEM_TIME AS OF must reference the "
                "stream side's event-time column "
                f"({left.time_field!r})")
        # the ON clause: exactly one equality between a left column and
        # the lookup table's key column
        conjuncts = _split_conjuncts(join.condition)
        if len(conjuncts) != 1 or not (
                isinstance(conjuncts[0], BinaryOp)
                and conjuncts[0].op == "="):
            raise PlanError(
                "lookup join requires exactly one equality predicate "
                "(left_col = dim_key)")
        eq = conjuncts[0]

        def _unqualify(e: Expr) -> Optional[Column]:
            if not isinstance(e, Column):
                return None
            return Column(e.name)

        sides = {}
        for e in (eq.left, eq.right):
            c = _unqualify(e)
            if c is None:
                raise PlanError(
                    "lookup join ON sides must be plain columns")
            q = e.table
            if q == r_alias or (q is None and c.name in r_columns
                                and c.name not in left.columns):
                sides["r"] = c
            else:
                sides["l"] = c
        if set(sides) != {"l", "r"}:
            raise PlanError(
                "lookup join ON must equate a stream column with the "
                "lookup table's key column")
        if sides["r"].name != fn.key_column:
            raise PlanError(
                f"lookup table {join.right.name!r} is keyed by "
                f"{fn.key_column!r}; ON references {sides['r'].name!r}")
        key_field = sides["l"].name
        if key_field not in left.columns:
            raise PlanError(
                f"lookup join key {key_field!r} is not a column of the "
                "stream side")
        t = Transformation(
            name="sql_lookup_join", kind="one_input",
            operator_factory=lambda: LookupJoinOperator(
                fn, key_field, right_columns=r_columns,
                suffixes=("_l", "_r"),
                cache_size=cache_size, cache_ttl_ms=cache_ttl_ms,
                left_outer=left_outer),
            inputs=[left.stream.transformation])
        joined = DataStream(self.env, t)
        out_cols: List[str] = []
        for c in left.columns:
            out_cols.append(c + "_l" if c in r_columns else c)
        for c in r_columns:
            out_cols.append(c + "_r" if c in left.columns else c)
        return PlannedTable(joined, out_cols, None,
                            left.time_field
                            if left.time_field in out_cols else None)

    def _lower_keyed_join(self, left: PlannedTable, right: PlannedTable,
                          l_aliases, r_aliases,
                          equi: List[Tuple[Expr, Expr]],
                          residual: List[Expr], op_factory,
                          name: str) -> PlannedTable:
        """Shared two-input keyed-join lowering: key both sides on the
        equi columns, wire the operator, suffix colliding output
        columns, and apply non-equi conjuncts as a post-filter."""
        l_stream = self._key_for_join(left, [l for l, _ in equi])
        r_stream = self._key_for_join(right, [r for _, r in equi])
        t = Transformation(
            name=name, kind="two_input", operator_factory=op_factory,
            inputs=[l_stream.transformation, r_stream.transformation],
            keyed=True)
        joined = DataStream(self.env, t)

        out_cols: List[str] = []
        for c in left.columns:
            out_cols.append(c + "_l" if c in right.columns else c)
        for c in right.columns:
            out_cols.append(c + "_r" if c in left.columns else c)

        if residual:
            # on an alias collision (self-join without aliases) the left
            # mapping wins, matching the historical behavior
            aliases = {k: "_r" for k in r_aliases}
            aliases.update({k: "_l" for k in l_aliases})
            res = [self._resolve(c, out_cols, aliases) for c in residual]

            def res_filter(batch, res=tuple(res)):
                mask = np.ones(len(batch), dtype=bool)
                for e in res:
                    mask &= np.asarray(e.eval(batch)).astype(bool)
                return mask

            joined = joined.filter(res_filter, name=f"{name}_residual")
        return PlannedTable(joined, out_cols, None, None)

    def _plan_temporal_join(self, join: ast.Join) -> PlannedTable:
        """JOIN versioned FOR SYSTEM_TIME AS OF left.rowtime ON k = k —
        each left row joins the right VERSION valid at its event time
        (reference: StreamExecTemporalJoin ->
        TemporalRowTimeJoinOperator; the right side is a versioned
        stream: its rows are versions keyed by the join key, versioned
        by their rowtime)."""
        from flink_tpu.runtime.join_operators import TemporalJoinOperator

        if isinstance(join.right, ast.NamedTable) and \
                join.right.name in self.t_env._lookup_tables:
            return self._plan_lookup_join(join)
        if join.kind != "INNER":
            raise PlanError(
                "temporal join supports INNER only (the reference "
                "default); LEFT temporal join is not supported yet")
        left = self._plan_table_ref(join.left)
        right = self._plan_table_ref(join.right)
        if left.upsert_keys is not None or right.upsert_keys is not None:
            raise PlanError(
                "temporal join inputs must be insert-only streams")
        if left.time_field is None or right.time_field is None:
            raise PlanError(
                "temporal join requires event-time (WATERMARK) on both "
                "sides: the left drives the as-of instant, the right's "
                "rowtime versions its rows")
        l_aliases = self._collect_aliases(join.left)
        r_aliases = self._collect_aliases(join.right)
        # the AS OF expression must be the LEFT side's rowtime
        as_of = join.temporal
        as_of_side = self._side_of(as_of, left, right,
                                   l_aliases, r_aliases)
        as_of_col = self._strip(as_of, left, l_aliases)
        if as_of_side != "l" or not isinstance(as_of_col, Column) \
                or as_of_col.name != left.time_field:
            raise PlanError(
                "FOR SYSTEM_TIME AS OF must reference the left input's "
                f"event-time column ({left.time_field!r})")
        conjuncts = _split_conjuncts(join.condition)
        equi: List[Tuple[Expr, Expr]] = []
        residual: List[Expr] = []
        for c in conjuncts:
            pair = self._match_equi(c, left, right, l_aliases, r_aliases)
            if pair is not None:
                equi.append(pair)
            else:
                residual.append(c)
        if not equi:
            raise PlanError(
                "temporal join requires an equality predicate on the "
                "version key")
        return self._lower_keyed_join(
            left, right, l_aliases, r_aliases, equi, residual,
            lambda: TemporalJoinOperator(suffixes=("_l", "_r")),
            "sql_temporal_join")

    def _side_of(self, expr: Expr, left: PlannedTable, right: PlannedTable,
                 l_aliases, r_aliases) -> Optional[str]:
        """'l' | 'r' | None (ambiguous/mixed)."""
        sides = set()
        for node in expr.walk():
            if isinstance(node, Column):
                if node.table is not None:
                    if node.table in l_aliases:
                        sides.add("l")
                    elif node.table in r_aliases:
                        sides.add("r")
                    else:
                        return None
                else:
                    in_l = node.name in left.columns
                    in_r = node.name in right.columns
                    if in_l and not in_r:
                        sides.add("l")
                    elif in_r and not in_l:
                        sides.add("r")
                    else:
                        return None
        return sides.pop() if len(sides) == 1 else None

    def _match_equi(self, c: Expr, left, right, l_aliases, r_aliases
                    ) -> Optional[Tuple[Expr, Expr]]:
        if not (isinstance(c, BinaryOp) and c.op == "="):
            return None
        ls = self._side_of(c.left, left, right, l_aliases, r_aliases)
        rs = self._side_of(c.right, left, right, l_aliases, r_aliases)
        if ls == "l" and rs == "r":
            return (self._strip(c.left, left, l_aliases),
                    self._strip(c.right, right, r_aliases))
        if ls == "r" and rs == "l":
            return (self._strip(c.right, left, l_aliases),
                    self._strip(c.left, right, r_aliases))
        return None

    def _strip(self, expr: Expr, table: PlannedTable, aliases) -> Expr:
        return self._resolve(expr, table.columns, {k: "" for k in aliases})

    def _match_time_bound(self, c: Expr, left, right, l_aliases, r_aliases
                          ) -> Optional[Tuple[int, int]]:
        """BETWEEN over opposite-side time attributes -> (lower, upper)
        offsets for right.ts relative to left.ts."""
        if not isinstance(c, Between):
            return None
        vs = self._side_of(c.value, left, right, l_aliases, r_aliases)
        los = self._side_of(c.low, left, right, l_aliases, r_aliases)
        his = self._side_of(c.high, left, right, l_aliases, r_aliases)
        if vs is None or los != his or los is None or vs == los:
            return None
        if vs == "l":
            val_delta = self._time_delta(c.value, left, l_aliases)
            lo = self._bound_delta(c.low, right, r_aliases)
            hi = self._bound_delta(c.high, right, r_aliases)
            if None in (val_delta, lo, hi):
                return None
            # l_ts + vd in [r_ts + lo, r_ts + hi]
            # -> r_ts in [l_ts + vd - hi, l_ts + vd - lo]
            return (val_delta - hi, val_delta - lo)
        val_delta = self._time_delta(c.value, right, r_aliases)
        lo = self._bound_delta(c.low, left, l_aliases)
        hi = self._bound_delta(c.high, left, l_aliases)
        if None in (val_delta, lo, hi):
            return None
        # r_ts + vd in [l_ts + lo, l_ts + hi]
        return (lo - val_delta, hi - val_delta)

    def _bound_delta(self, expr: Expr, table, aliases) -> Optional[int]:
        """Resolve `time_attr +- literal` to an offset vs the side's __ts__."""
        if isinstance(expr, BinaryOp) and expr.op in ("+", "-"):
            if isinstance(expr.right, Literal):
                base = self._time_delta(expr.left, table, aliases)
                if base is None:
                    return None
                off = int(expr.right.value)
                return base + off if expr.op == "+" else base - off
        return self._time_delta(expr, table, aliases)

    def _time_delta(self, expr: Expr, table: PlannedTable, aliases
                    ) -> Optional[int]:
        e = self._strip(expr, table, aliases)
        if isinstance(e, Column):
            if table.time_field is not None and e.name == table.time_field:
                return 1 if e.name == WINDOW_END_FIELD else 0
            if e.name == WINDOW_END_FIELD:
                # window results carry __ts__ = window_end - 1
                return 1
            if table.time_field is None:
                # trust the declared event-time column == __ts__
                return 0
        return None

    def _key_for_join(self, table: PlannedTable, key_exprs: List[Expr]
                      ) -> DataStream:
        """Key a side by the join-key expressions. Values are canonicalized
        (numerics -> float64) so that e.g. an int64 `price` joins a float32
        `maxprice` — the two sides' key hashes must agree even though column
        dtypes differ (the reference normalizes via its type system)."""
        stream = table.stream
        name = GROUP_KEY_FIELD

        def add_key(batch, exprs=tuple(key_exprs)):
            vals = []
            for e in exprs:
                v = np.asarray(e.eval(batch))
                vals.append(v.astype(np.float64)
                            if v.dtype.kind in "iufb" else v)
            if len(vals) == 1:
                return batch.with_column(name, vals[0])
            tuples = list(zip(*[v.tolist() for v in vals]))
            arr = np.empty(len(batch), dtype=object)
            arr[:] = tuples
            return batch.with_column(name, arr)

        return stream.map(add_key, name="sql_join_key").key_by(name)

    # ------------------------------------------------------------ finishing

    def _finish(self, stream: DataStream, names: List[str],
                source: PlannedTable, stmt: ast.SelectStmt) -> PlannedTable:
        planned = PlannedTable(stream, names, source.alias,
                               source.time_field
                               if source.time_field in names else None,
                               source.upsert_keys)
        return self._apply_order_limit(planned, stmt)

    def _apply_order_limit(self, planned: PlannedTable,
                           stmt: ast.SelectStmt) -> PlannedTable:
        if stmt.order_by or stmt.limit is not None:
            planned.sort_spec = [(o.expr, o.descending)
                                 for o in stmt.order_by]
            planned.limit = stmt.limit
        return planned


def _window_assigner(tvf: ast.WindowTVF):
    if tvf.kind == "TUMBLE":
        return TumblingEventTimeWindows.of(tvf.size_ms)
    if tvf.kind == "HOP":
        return SlidingEventTimeWindows.of(tvf.size_ms, tvf.slide_ms)
    if tvf.kind == "CUMULATE":
        return CumulativeEventTimeWindows(tvf.size_ms, tvf.slide_ms)
    if tvf.kind == "SESSION":
        return EventTimeSessionWindows.with_gap(tvf.size_ms)
    raise PlanError(f"unknown window kind {tvf.kind}")


# one conjunct-flattening implementation for the whole table layer
from flink_tpu.table.optimizer import split_conjuncts as _split_conjuncts  # noqa: E402
