"""EXPLAIN renderer: optimized logical plan + chained physical plan.

reference: TableEnvironment.explainSql — Calcite's AST / optimized rel
plan / physical execution plan sections. Here the logical section is the
optimizer's output rendered back to SQL-ish text, and the physical
section is the chained JobGraph (graph/job_graph.py) the query's stream
would execute as.
"""

from __future__ import annotations

from typing import List

from flink_tpu.table import sql_parser as ast
from flink_tpu.table.expressions import (
    AggCall,
    Between,
    BinaryOp,
    Case,
    Cast,
    Column,
    Expr,
    InList,
    Literal,
    OverCall,
    ScalarFunc,
    Star,
    UnaryOp,
)


def render_expr(e: Expr) -> str:
    if isinstance(e, Column):
        return f"{e.table}.{e.name}" if e.table else e.name
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, Star):
        return "*"
    if isinstance(e, BinaryOp):
        return f"({render_expr(e.left)} {e.op} {render_expr(e.right)})"
    if isinstance(e, UnaryOp):
        return f"({e.op} {render_expr(e.operand)})"
    if isinstance(e, Between):
        return (f"({render_expr(e.value)} BETWEEN "
                f"{render_expr(e.low)} AND {render_expr(e.high)})")
    if isinstance(e, InList):
        inner = ", ".join(repr(o) for o in e.options)
        neg = "NOT " if e.negated else ""
        return f"({render_expr(e.value)} {neg}IN ({inner}))"
    if isinstance(e, AggCall):
        arg = render_expr(e.arg) if e.arg is not None else "*"
        d = "DISTINCT " if e.distinct else ""
        return f"{e.func}({d}{arg})"
    if isinstance(e, OverCall):
        parts = []
        if e.partition_by:
            parts.append("PARTITION BY " + ", ".join(
                render_expr(x) for x in e.partition_by))
        if e.order_by:
            parts.append("ORDER BY " + ", ".join(
                render_expr(x) + (" DESC" if desc else "")
                for x, desc in e.order_by))
        return f"{e.func}() OVER ({' '.join(parts)})"
    if isinstance(e, ScalarFunc):
        return f"{e.name}({', '.join(render_expr(a) for a in e.args)})"
    if isinstance(e, Cast):
        return f"CAST({render_expr(e.operand)} AS {e.type_name})"
    if isinstance(e, Case):
        parts = ["CASE"]
        for c, v in e.whens:
            parts.append(f"WHEN {render_expr(c)} THEN {render_expr(v)}")
        if e.default is not None:
            parts.append(f"ELSE {render_expr(e.default)}")
        parts.append("END")
        return " ".join(parts)
    return repr(e)


def _render_ref(ref, indent: str) -> List[str]:
    if isinstance(ref, ast.NamedTable):
        alias = f" AS {ref.alias}" if ref.alias else ""
        return [f"{indent}{ref.name}{alias}"]
    if isinstance(ref, ast.SubQuery):
        out = [f"{indent}({ref.alias or 'subquery'}):"]
        out.extend(render_stmt(ref.query, indent + "  "))
        return out
    if isinstance(ref, ast.WindowTVF):
        head = (f"{indent}{ref.kind}(time_col={ref.time_col}, "
                f"size={ref.size_ms}ms"
                + (f", slide={ref.slide_ms}ms" if ref.slide_ms else "")
                + ") over:")
        return [head] + _render_ref(ref.table, indent + "  ")
    if isinstance(ref, ast.Join):
        out = [f"{indent}{ref.kind} JOIN ON "
               f"{render_expr(ref.condition)}:"]
        out.extend(_render_ref(ref.left, indent + "  "))
        out.extend(_render_ref(ref.right, indent + "  "))
        return out
    if isinstance(ref, ast.MLPredictTVF):
        return ([f"{indent}ML_PREDICT(model={ref.model}, "
                 f"on={ref.fields}) over:"]
                + _render_ref(ref.table, indent + "  "))
    table = getattr(ref, "table", None)
    if table is not None and hasattr(table, "columns"):  # fluent inline
        return [f"{indent}<inline table {table.columns}>"]
    return [f"{indent}{ref!r}"]


def render_stmt(stmt, indent: str = "") -> List[str]:
    if isinstance(stmt, ast.UnionAll):
        out = [f"{indent}UNION ALL:"]
        for s in stmt.selects:
            out.extend(render_stmt(s, indent + "  "))
        if stmt.order_by:
            out.append(f"{indent}ORDER BY " + ", ".join(
                render_expr(o.expr) + (" DESC" if o.descending else "")
                for o in stmt.order_by))
        if stmt.limit is not None:
            out.append(f"{indent}LIMIT {stmt.limit}")
        return out
    out = [indent + "SELECT "
           + ("DISTINCT " if stmt.distinct else "")
           + ", ".join(
               render_expr(i.expr) + (f" AS {i.alias}" if i.alias else "")
               for i in stmt.items)]
    out.append(f"{indent}FROM")
    out.extend(_render_ref(stmt.table, indent + "  "))
    if stmt.where is not None:
        out.append(f"{indent}WHERE {render_expr(stmt.where)}")
    if stmt.group_by:
        out.append(f"{indent}GROUP BY " + ", ".join(
            render_expr(g) for g in stmt.group_by))
    if stmt.having is not None:
        out.append(f"{indent}HAVING {render_expr(stmt.having)}")
    if stmt.order_by:
        out.append(f"{indent}ORDER BY " + ", ".join(
            render_expr(o.expr) + (" DESC" if o.descending else "")
            for o in stmt.order_by))
    if stmt.limit is not None:
        out.append(f"{indent}LIMIT {stmt.limit}")
    return out


def explain(t_env, optimized_stmt, planned) -> str:
    """The EXPLAIN text: optimized logical plan + chained physical plan
    of the planned stream."""
    from flink_tpu.graph.job_graph import build_job_graph
    from flink_tpu.graph.transformations import StreamGraph

    lines = ["== Optimized Logical Plan =="]
    lines.extend(render_stmt(optimized_stmt))
    lines.append("")
    lines.append("== Physical Plan (chained job graph) ==")
    graph = StreamGraph([planned.stream.transformation])
    jg = build_job_graph(
        graph, default_parallelism=t_env.env.parallelism
        if hasattr(t_env.env, "parallelism") else 1)
    for v in jg.vertices:
        lines.append(f"vertex {v.vid} (parallelism {v.parallelism}): "
                     f"{v.name}")
    for e in jg.edges:
        key = f" key={e.key_field}" if e.key_field else ""
        lines.append(f"  {e.source_vid} -> {e.target_vid} "
                     f"[{e.ship}{key}]")
    if planned.sort_spec or planned.limit is not None:
        deco = []
        if planned.sort_spec:
            deco.append("sort=" + ", ".join(
                render_expr(x) + (" DESC" if d else "")
                for x, d in planned.sort_spec))
        if planned.limit is not None:
            deco.append(f"limit={planned.limit}")
        lines.append("materialization: " + "; ".join(deco))
    return "\n".join(lines)
