"""Rule-based logical optimizer over the SQL AST.

reference: the Calcite rule sets the reference's planner applies before
translation (flink-table-planner/.../plan/rules/FlinkStreamRuleSets.scala —
CoreRules.FILTER_INTO_JOIN, FILTER_PROJECT_TRANSPOSE / FlinkFilterJoinRule,
constant reduction via ReduceExpressionsRule). The re-design keeps the
same shape at a fraction of the machinery: a handful of AST -> AST rewrite
rules applied bottom-up until fixpoint, feeding the direct translator
(flink_tpu/table/planner.py).

Rules:
- **constant folding** — Literal-only subtrees collapse (1 + 2 -> 3,
  TRUE AND p -> p, FALSE AND p -> FALSE), shrinking per-batch expression
  evaluation to what actually depends on data.
- **filter pushdown into joins** — WHERE conjuncts whose columns are all
  qualified to one join side move below the join (both sides for INNER,
  only the preserved side for LEFT: filtering the null-supplying side
  above vs below a LEFT join differ). Join state is the dominant memory
  cost of the streaming equi-join; filtering before buffering shrinks it.
- **filter pushdown into subqueries** — a predicate over a non-aggregating
  subquery moves inside it (columns substituted through the inner select
  list), so it runs before whatever the subquery buffers downstream.

All rules are semantics-preserving for the streaming subset the planner
accepts; anything the rules cannot prove is left where it was.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from flink_tpu.table import sql_parser as ast
from flink_tpu.table.expressions import (
    Between,
    BinaryOp,
    Case,
    Cast,
    Column,
    Expr,
    InList,
    Literal,
    ScalarFunc,
    SelectItem,
    Star,
    UnaryOp,
)

# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------

_FOLD_BIN = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
    "=": lambda a, b: a == b,
    "<>": lambda a, b: a != b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def _is_lit(e: Expr, value=None) -> bool:
    if not isinstance(e, Literal):
        return False
    if value is None:
        return True
    # Boolean identities must only match genuine booleans: Python's 1 == True
    # would otherwise fold integer-in-boolean-context SQL (WHERE 1 AND p)
    # that the unfolded path evaluates differently (or rejects).
    if isinstance(value, bool) and not isinstance(e.value, bool):
        return False
    return e.value == value


def fold_constants(expr: Expr) -> Expr:
    """Bottom-up constant folding + boolean identity simplification."""
    if isinstance(expr, BinaryOp):
        left = fold_constants(expr.left)
        right = fold_constants(expr.right)
        if expr.op == "AND":
            if _is_lit(left, True):
                return right
            if _is_lit(right, True):
                return left
            if _is_lit(left, False) or _is_lit(right, False):
                return Literal(False)
        elif expr.op == "OR":
            if _is_lit(left, False):
                return right
            if _is_lit(right, False):
                return left
            if _is_lit(left, True) or _is_lit(right, True):
                return Literal(True)
        elif isinstance(left, Literal) and isinstance(right, Literal) \
                and expr.op in _FOLD_BIN:
            try:
                return Literal(_FOLD_BIN[expr.op](left.value, right.value))
            except Exception:  # e.g. divide by zero: leave for runtime
                pass
        return BinaryOp(expr.op, left, right)
    if isinstance(expr, UnaryOp):
        operand = fold_constants(expr.operand)
        if isinstance(operand, Literal):
            try:  # type mismatches stay for runtime, like the BinaryOp path
                if expr.op == "NOT":
                    return Literal(not operand.value)
                if expr.op == "-":
                    return Literal(-operand.value)
            except Exception:
                pass
        return UnaryOp(expr.op, operand)
    if isinstance(expr, Between):
        value = fold_constants(expr.value)
        low = fold_constants(expr.low)
        high = fold_constants(expr.high)
        if all(isinstance(e, Literal) for e in (value, low, high)):
            try:
                return Literal(low.value <= value.value <= high.value)
            except Exception:
                pass
        return Between(value, low, high)
    if isinstance(expr, InList):
        value = fold_constants(expr.value)
        if isinstance(value, Literal):
            try:
                hit = value.value in expr.options
                return Literal(not hit if expr.negated else hit)
            except Exception:
                pass
        return InList(value, expr.options, expr.negated)
    if isinstance(expr, Case):
        whens = tuple((fold_constants(c), fold_constants(v))
                      for c, v in expr.whens)
        default = fold_constants(expr.default) \
            if expr.default is not None else None
        return Case(whens, default)
    if isinstance(expr, Cast):
        return Cast(fold_constants(expr.operand), expr.type_name)
    if isinstance(expr, ScalarFunc):
        return ScalarFunc(expr.name,
                          tuple(fold_constants(a) for a in expr.args))
    return expr


# ---------------------------------------------------------------------------
# conjunct utilities
# ---------------------------------------------------------------------------


def split_conjuncts(expr: Optional[Expr]) -> List[Expr]:
    if expr is None:
        return []
    if isinstance(expr, BinaryOp) and expr.op == "AND":
        return split_conjuncts(expr.left) + split_conjuncts(expr.right)
    return [expr]


def and_all(conjuncts: List[Expr]) -> Optional[Expr]:
    out: Optional[Expr] = None
    for c in conjuncts:
        out = c if out is None else BinaryOp("AND", out, c)
    return out


def _ref_aliases(ref: ast.TableRef) -> List[str]:
    """The alias names under which this table ref's columns are qualified."""
    if isinstance(ref, ast.NamedTable):
        # Standard SQL scoping: an alias hides the base table name, so a
        # self-join (FROM t a JOIN t b) must not match `t.x` to either side.
        return [ref.alias] if ref.alias else [ref.name]
    if isinstance(ref, (ast.SubQuery, ast.MLPredictTVF)):
        out = [ref.alias] if ref.alias else []
        if isinstance(ref, ast.MLPredictTVF):
            out.extend(_ref_aliases(ref.table))
        return out
    if isinstance(ref, ast.WindowTVF):
        out = [ref.alias] if ref.alias else []
        out.extend(_ref_aliases(ref.table))
        return out
    return []


def _side_of_conjunct(c: Expr, left_aliases: List[str],
                      right_aliases: List[str]) -> Optional[str]:
    """'l' / 'r' when every column is qualified to exactly that side
    (unqualified columns are ambiguous -> no push)."""
    sides = set()
    for n in c.walk():
        if isinstance(n, Column):
            if n.table is None:
                return None
            if n.table in left_aliases:
                sides.add("l")
            elif n.table in right_aliases:
                sides.add("r")
            else:
                return None
    if len(sides) == 1:
        return sides.pop()
    return None


def _wrap_with_filter(ref: ast.TableRef, conjuncts: List[Expr]
                      ) -> ast.TableRef:
    """side -> SELECT * FROM side WHERE conjuncts (alias preserved so
    outer qualified references keep resolving)."""
    if isinstance(ref, ast.SubQuery) and _pushable_subquery(ref.query):
        inner = _push_into_select(ref.query, conjuncts, ref.alias)
        if inner is not None:
            return ast.SubQuery(inner, ref.alias)
    alias = ref.alias if not isinstance(ref, ast.NamedTable) \
        else (ref.alias or ref.name)
    # inner qualifiers keep working: the wrapped ref retains its own alias
    stmt = ast.SelectStmt(items=[SelectItem(Star(), None)], table=ref,
                          where=and_all(conjuncts))
    return ast.SubQuery(stmt, alias)


# ---------------------------------------------------------------------------
# filter pushdown
# ---------------------------------------------------------------------------


def _pushable_subquery(stmt: ast.SelectStmt) -> bool:
    """A subquery a predicate can safely move into: no aggregation,
    dedup, windowing, row-limiting, or OVER windows between the
    predicate's old and new positions (the rank/Top-N pattern NEEDS its
    ``rownum <= N`` filter to stay above the ROW_NUMBER subquery — that
    filter is how the planner recognizes Top-N). UNION ALL subqueries
    are left alone (pushing would have to fan the predicate out per
    branch)."""
    from flink_tpu.table.expressions import OverCall

    if not isinstance(stmt, ast.SelectStmt):
        return False

    return (not stmt.group_by and not stmt.having and not stmt.distinct
            and stmt.limit is None and not stmt.order_by
            and not isinstance(stmt.table, ast.WindowTVF)
            and not any(
                i.expr.aggregates()
                or any(isinstance(n, OverCall) for n in i.expr.walk())
                for i in stmt.items if not isinstance(i.expr, Star)))


def _push_into_select(stmt: ast.SelectStmt, conjuncts: List[Expr],
                      outer_alias: Optional[str]
                      ) -> Optional[ast.SelectStmt]:
    """Rewrite predicate columns through the select list and AND them into
    the subquery's WHERE. Returns None when any column cannot be mapped."""
    mapping: Dict[str, Expr] = {}
    has_star = False
    for item in stmt.items:
        if isinstance(item.expr, Star):
            has_star = True
            continue
        name = item.alias or item.expr.output_name()
        mapping[name] = item.expr
    rewritten: List[Expr] = []
    for c in conjuncts:
        ok = True

        def sub(e: Expr) -> Expr:
            nonlocal ok
            if isinstance(e, Column):
                if e.table is not None and outer_alias is not None \
                        and e.table != outer_alias:
                    ok = False
                    return e
                if e.name in mapping:
                    return mapping[e.name]
                if has_star:
                    # passes through the star projection untouched; drop
                    # the (outer) qualifier — inner scope resolves it
                    return Column(e.name, None)
                ok = False
                return e
            if isinstance(e, BinaryOp):
                return BinaryOp(e.op, sub(e.left), sub(e.right))
            if isinstance(e, UnaryOp):
                return UnaryOp(e.op, sub(e.operand))
            if isinstance(e, Between):
                return Between(sub(e.value), sub(e.low), sub(e.high))
            if isinstance(e, InList):
                return InList(sub(e.value), e.options, e.negated)
            if isinstance(e, Cast):
                return Cast(sub(e.operand), e.type_name)
            if isinstance(e, ScalarFunc):
                return ScalarFunc(e.name, tuple(sub(a) for a in e.args))
            if isinstance(e, (Literal,)):
                return e
            ok = False  # Case/OverCall/AggCall etc.: leave outside
            return e

        r = sub(c)
        if not ok:
            return None
        rewritten.append(r)
    return dataclasses.replace(
        stmt, where=and_all(split_conjuncts(stmt.where) + rewritten))


def _optimize_select(stmt: ast.SelectStmt) -> ast.SelectStmt:
    # bottom-up: optimize nested select statements first
    table = _optimize_ref(stmt.table)
    where = fold_constants(stmt.where) if stmt.where is not None else None
    having = fold_constants(stmt.having) if stmt.having is not None else None
    items = [SelectItem(i.expr if isinstance(i.expr, Star)
                        else fold_constants(i.expr), i.alias)
             for i in stmt.items]
    if where is not None and _is_lit(where, True):
        where = None

    conjuncts = split_conjuncts(where)
    kept: List[Expr] = []

    if isinstance(table, ast.Join) and conjuncts:
        left_aliases = _ref_aliases(table.left)
        right_aliases = _ref_aliases(table.right)
        push_l: List[Expr] = []
        push_r: List[Expr] = []
        for c in conjuncts:
            side = _side_of_conjunct(c, left_aliases, right_aliases)
            if side == "l":
                push_l.append(c)
            elif side == "r" and table.kind == "INNER":
                # LEFT join: the null-supplying side's predicate must stay
                # above the join (it would drop null-extended rows anyway,
                # but pushing changes WHICH rows null-extend)
                push_r.append(c)
            else:
                kept.append(c)
        left = _wrap_with_filter(table.left, push_l) if push_l \
            else table.left
        right = _wrap_with_filter(table.right, push_r) if push_r \
            else table.right
        table = ast.Join(left, right, table.kind, table.condition,
                         temporal=table.temporal)
        where = and_all(kept)
    elif isinstance(table, ast.SubQuery) and conjuncts \
            and _pushable_subquery(table.query):
        inner = _push_into_select(table.query, conjuncts, table.alias)
        if inner is not None:
            table = ast.SubQuery(_optimize_select(inner), table.alias)
            where = None

    return dataclasses.replace(stmt, table=table, where=where,
                               having=having, items=items)


def _optimize_ref(ref: ast.TableRef) -> ast.TableRef:
    if isinstance(ref, ast.SubQuery):
        return ast.SubQuery(optimize(ref.query), ref.alias)
    if isinstance(ref, ast.Join):
        return ast.Join(_optimize_ref(ref.left), _optimize_ref(ref.right),
                        ref.kind, fold_constants(ref.condition),
                        temporal=ref.temporal)
    if isinstance(ref, ast.WindowTVF):
        return dataclasses.replace(ref, table=_optimize_ref(ref.table))
    if isinstance(ref, ast.MLPredictTVF):
        return dataclasses.replace(ref, table=_optimize_ref(ref.table))
    return ref


def optimize(stmt):
    """The planner's pre-pass: apply the rule set to fixpoint (two passes
    suffice — pushdown exposes at most one new fold opportunity layer,
    and the rules strictly shrink/sink predicates)."""
    if isinstance(stmt, ast.UnionAll):
        return dataclasses.replace(
            stmt, selects=[optimize(s) for s in stmt.selects])
    return _optimize_select(_optimize_select(stmt))
