"""SinkUpsertMaterializer — collapse a changelog before an upsert sink.

reference: flink-table/flink-table-runtime/src/main/java/org/apache/flink/
table/runtime/operators/sink/SinkUpsertMaterializer.java:1 — an operator
keyed on the sink's upsert key that turns the upstream changelog
(+I / -U / +U / -D rows) into a last-row-wins UPSERT stream: at most one
row per key per emission, either the key's new current image (+I first
time, +U after) or a DELETE tombstone. This is what lets
``INSERT INTO kafka_table SELECT k, COUNT(*) FROM t GROUP BY k`` — a
plain updating aggregate written to an external table — run at all.

Re-design: the upstream changelog is columnar and per-key ordered (the
GroupAgg operator emits -U(prev) immediately before +U(new)), so the
collapse is vectorized where it counts: drop UPDATE_BEFORE pre-images,
take the LAST effective row per key in the batch, diff against the
materialized current image, and emit one row per touched key. Restore is
key-group filtered so the operator re-shards across subtask counts like
every keyed state here.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from flink_tpu.core.records import (
    ROWKIND_DELETE,
    ROWKIND_FIELD,
    ROWKIND_INSERT,
    ROWKIND_UPDATE_AFTER,
    ROWKIND_UPDATE_BEFORE,
    TIMESTAMP_FIELD,
    RecordBatch,
)
from flink_tpu.runtime.operators import Operator


class UpsertMaterializeOperator(Operator):
    """Keyed changelog materialization (SinkUpsertMaterializer).

    Per sink key, the operator keeps the LIST of row images currently
    contributing to that key — the reference's exact algorithm, which is
    what makes a changelog whose own key differs from the sink PRIMARY
    KEY (e.g. a global aggregate written into a value-keyed table)
    materialize correctly: an add appends its row, a retraction removes
    the matching row, and the key's emitted image is the list's last
    row (or a DELETE tombstone when the list drains). Emission is
    collapsed per key per batch: one row per touched key — the new
    current image (+I first time, +U after) or -D."""

    name = "sink_upsert_materializer"

    def __init__(self, upsert_keys: List[str],
                 ttl_ms: Optional[int] = None, clock=None):
        if not upsert_keys:
            raise ValueError("upsert materializer requires upsert keys")
        from flink_tpu.state.ttl import SweepGate, default_clock

        self.upsert_keys = list(upsert_keys)
        #: table.exec.state.ttl: a sink key untouched this long drops its
        #: image list (reference: SinkUpsertMaterializer registers a
        #: state-retention cleanup timer per key)
        self.ttl_ms = ttl_ms
        self._clock = clock or default_clock
        self._sweep_gate = SweepGate(ttl_ms) if ttl_ms else None
        #: sink-key tuple -> last-touch processing time (TTL only)
        self._access: Dict[Tuple, int] = {}
        #: sink-key tuple -> list of contributing row-value tuples
        self._rows: Dict[Tuple, List[Tuple]] = {}
        #: column order of the row-value tuples (fixed at first batch)
        self._cols: List[str] = []
        #: positions of _cols compared when matching a retraction —
        #: everything except the event-time stamp. Upstream GroupAgg
        #: re-stamps every emission (including -U pre-images) with its
        #: CURRENT watermark-side max_ts, so a -U's __ts__ never equals
        #: the stored image's once event time advances; matching on the
        #: full tuple would then fall to the drop-oldest path and remove
        #: the WRONG image when several changelog keys feed one sink key
        #: (the reference removes by row equality over VALUES —
        #: SinkUpsertMaterializer.java's equaliser compares row fields,
        #: not system timestamps).
        self._match_idx: List[int] = []

    def open(self, ctx) -> None:
        self.max_parallelism = getattr(ctx, "max_parallelism", 128)

    # ------------------------------------------------------------- process

    def process_batch(self, batch: RecordBatch,
                      input_index: int = 0) -> List[RecordBatch]:
        n = len(batch)
        if n == 0:
            return []
        missing = [k for k in self.upsert_keys if k not in batch.columns]
        if missing:
            raise RuntimeError(
                f"upsert materializer: key columns {missing} missing "
                f"from changelog batch (columns: {batch.names()})")
        value_cols = [c for c in batch.names() if c != ROWKIND_FIELD]
        if not self._cols:
            self._cols = value_cols
        if not self._match_idx:
            self._match_idx = [i for i, c in enumerate(self._cols)
                               if c != TIMESTAMP_FIELD]
        kinds = (np.asarray(batch[ROWKIND_FIELD])
                 if ROWKIND_FIELD in batch.columns
                 else np.full(n, ROWKIND_INSERT, dtype=np.int8))
        col_lists = [batch[c].tolist() for c in self._cols]
        rows = list(zip(*col_lists))
        key_idx = [self._cols.index(k) for k in self.upsert_keys]
        now = self._clock() if self.ttl_ms else 0
        #: key -> image before this batch (None = absent), captured at
        #: the key's first touch so the batch collapses to one emission
        before: Dict[Tuple, Any] = {}
        for row, kind in zip(rows, kinds):
            k = tuple(row[i] for i in key_idx)
            lst = self._rows.get(k)
            if self.ttl_ms:
                self._access[k] = now
            if k not in before:
                before[k] = lst[-1] if lst else None
            if int(kind) in (ROWKIND_INSERT, ROWKIND_UPDATE_AFTER):
                if lst is None:
                    lst = self._rows[k] = []
                lst.append(row)
                continue
            # retraction (-U / -D): remove the LAST matching image
            # (reference: SinkUpsertMaterializer removes by row
            # equality; a miss means an upstream inconsistency and is
            # tolerated by dropping the oldest)
            if not lst:
                continue
            probe = tuple(row[i] for i in self._match_idx)
            for i in range(len(lst) - 1, -1, -1):
                if tuple(lst[i][j] for j in self._match_idx) == probe:
                    del lst[i]
                    break
            else:
                del lst[0]
            if not lst:
                del self._rows[k]
        out_rows: List[Tuple] = []
        out_kinds: List[int] = []
        for k, prev in before.items():
            lst = self._rows.get(k)
            cur = lst[-1] if lst else None
            if cur is None:
                if prev is not None:
                    out_rows.append(prev)
                    out_kinds.append(ROWKIND_DELETE)
                continue
            if prev is None:
                out_rows.append(cur)
                out_kinds.append(ROWKIND_INSERT)
            elif (tuple(cur[j] for j in self._match_idx)
                  != tuple(prev[j] for j in self._match_idx)):
                # value columns changed (the restamped __ts__ alone is
                # not a change — same masking as retraction matching)
                out_rows.append(cur)
                out_kinds.append(ROWKIND_UPDATE_AFTER)
            # unchanged: suppress the duplicate upsert
        if not out_rows:
            return []
        cols = {c: np.asarray([r[i] for r in out_rows])
                for i, c in enumerate(self._cols)}
        cols[ROWKIND_FIELD] = np.asarray(out_kinds, dtype=np.int8)
        ts = cols.pop("__ts__", None)
        return [RecordBatch.from_pydict(cols, timestamps=ts)]

    # ------------------------------------------------------------------ TTL

    def process_watermark(self, watermark, input_index=0):
        self._maybe_sweep_ttl()
        return []

    def _maybe_sweep_ttl(self) -> None:
        if not self.ttl_ms:
            return
        now = self._clock()
        if not self._sweep_gate.should_sweep(now):
            return
        dead = [k for k, s in self._access.items()
                if now - s > self.ttl_ms]
        for k in dead:
            del self._access[k]
            self._rows.pop(k, None)

    # --------------------------------------------------------------- state

    def _key_ids(self, keys: List[Tuple]) -> np.ndarray:
        from flink_tpu.state.keygroups import hash_keys_to_i64

        first = np.asarray([k[0] for k in keys])
        return hash_keys_to_i64(first)

    def snapshot_state(self) -> Dict[str, Any]:
        keys = list(self._rows.keys())
        snap = {
            "um_cols": list(self._cols),
            "um_keys": keys,
            "um_rows": [self._rows[k] for k in keys],
        }
        if self.ttl_ms:
            snap["um_access"] = [self._access.get(k, 0) for k in keys]
        return snap

    def restore_state(self, state: Dict[str, Any],
                      key_group_filter=None) -> None:
        self._cols = list(state.get("um_cols", []))
        self._match_idx = [i for i, c in enumerate(self._cols)
                           if c != TIMESTAMP_FIELD]
        keys = [tuple(k) if isinstance(k, (list, tuple)) else (k,)
                for k in state.get("um_keys", [])]
        rows = [[tuple(r) for r in lst]
                for lst in state.get("um_rows", [])]
        access = list(state.get("um_access", []))
        if key_group_filter is not None and keys:
            from flink_tpu.state.keygroups import assign_key_groups

            groups = assign_key_groups(self._key_ids(keys),
                                       self.max_parallelism)
            keep = [g in key_group_filter for g in groups]
            keys = [k for k, ok in zip(keys, keep) if ok]
            rows = [r for r, ok in zip(rows, keep) if ok]
            if access:
                access = [a for a, ok in zip(access, keep) if ok]
        self._rows = dict(zip(keys, rows))
        self._access = dict(zip(keys, access)) if access else {}

    def close(self) -> List[RecordBatch]:
        return []
