"""Vectorized SQL expression evaluation.

Where the reference Janino-compiles each expression into a Java class
(reference: flink-table-planner/src/main/scala/.../codegen/ExprCodeGenerator.scala),
here an expression tree evaluates directly as vectorized NumPy over the
columns of a RecordBatch — one array op per node, no per-row interpretation.
Aggregate calls (SUM/COUNT/...) are *markers*: the planner lifts them out of
the tree and maps them onto device-side AggregateFunctions
(flink_tpu.windowing.aggregates); only the non-aggregate residue is evaluated
by this module.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.records import RecordBatch

# ---------------------------------------------------------------------------
# AST nodes
# ---------------------------------------------------------------------------


class Expr:
    def eval(self, batch: RecordBatch) -> np.ndarray:
        raise NotImplementedError

    def output_name(self) -> str:
        return "expr"

    def children(self) -> Sequence["Expr"]:
        return ()

    def walk(self):
        yield self
        for c in self.children():
            yield from c.walk()

    def columns_used(self) -> List[str]:
        return [n.name for n in self.walk() if isinstance(n, Column)]

    def aggregates(self) -> List["AggCall"]:
        out = []
        for n in self.walk():
            if isinstance(n, AggCall):
                out.append(n)
        return out

    def rewrite(self, mapping: Dict["Expr", "Expr"]) -> "Expr":
        """Structural replace (by equality) — used to swap AggCalls for
        Columns referencing their materialized result."""
        for k, v in mapping.items():
            if self == k:
                return v
        return self._rewrite_children(mapping)

    def _rewrite_children(self, mapping) -> "Expr":
        return self


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    value: Any

    def eval(self, batch):
        return np.full(len(batch), self.value)

    def output_name(self):
        return str(self.value)


@dataclasses.dataclass(frozen=True)
class Column(Expr):
    name: str
    table: Optional[str] = None  # qualifier, resolved/dropped at plan time

    def eval(self, batch):
        if self.name not in batch.columns:
            raise KeyError(
                f"column {self.name!r} not in batch columns {batch.names()}")
        return batch[self.name]

    def output_name(self):
        return self.name


@dataclasses.dataclass(frozen=True)
class Star(Expr):
    """SELECT * marker."""

    def output_name(self):
        return "*"


_BINOPS = {
    "+": np.add,
    "-": np.subtract,
    "*": np.multiply,
    "/": np.divide,
    "%": np.mod,
    "=": np.equal,
    "<>": np.not_equal,
    "!=": np.not_equal,
    "<": np.less,
    "<=": np.less_equal,
    ">": np.greater,
    ">=": np.greater_equal,
    "AND": np.logical_and,
    "OR": np.logical_or,
}


@dataclasses.dataclass(frozen=True)
class BinaryOp(Expr):
    op: str
    left: Expr
    right: Expr

    def eval(self, batch):
        lv = self.left.eval(batch)
        rv = self.right.eval(batch)
        if self.op in ("=", "<>", "!=") and (
                lv.dtype == object or rv.dtype == object):
            eq = np.array([a == b for a, b in zip(lv, rv)], dtype=bool)
            return eq if self.op == "=" else ~eq
        return _BINOPS[self.op](lv, rv)

    def children(self):
        return (self.left, self.right)

    def output_name(self):
        return f"{self.left.output_name()}_{self.op}_{self.right.output_name()}"

    def _rewrite_children(self, mapping):
        return BinaryOp(self.op, self.left.rewrite(mapping),
                        self.right.rewrite(mapping))


@dataclasses.dataclass(frozen=True)
class UnaryOp(Expr):
    op: str  # 'NOT' | '-'
    operand: Expr

    def eval(self, batch):
        v = self.operand.eval(batch)
        return np.logical_not(v) if self.op == "NOT" else np.negative(v)

    def children(self):
        return (self.operand,)

    def _rewrite_children(self, mapping):
        return UnaryOp(self.op, self.operand.rewrite(mapping))


@dataclasses.dataclass(frozen=True)
class Between(Expr):
    value: Expr
    low: Expr
    high: Expr

    def eval(self, batch):
        v = self.value.eval(batch)
        return (v >= self.low.eval(batch)) & (v <= self.high.eval(batch))

    def children(self):
        return (self.value, self.low, self.high)

    def _rewrite_children(self, mapping):
        return Between(self.value.rewrite(mapping), self.low.rewrite(mapping),
                       self.high.rewrite(mapping))


@dataclasses.dataclass(frozen=True)
class InList(Expr):
    value: Expr
    options: Tuple[Any, ...]
    negated: bool = False

    def eval(self, batch):
        v = self.value.eval(batch)
        mask = np.isin(v, np.asarray(list(self.options)))
        return ~mask if self.negated else mask

    def children(self):
        return (self.value,)

    def _rewrite_children(self, mapping):
        return InList(self.value.rewrite(mapping), self.options, self.negated)


@dataclasses.dataclass(frozen=True)
class Case(Expr):
    """CASE WHEN c1 THEN v1 ... ELSE d END — vectorized np.select."""

    whens: Tuple[Tuple[Expr, Expr], ...]
    default: Optional[Expr] = None

    def eval(self, batch):
        conds = [c.eval(batch).astype(bool) for c, _ in self.whens]
        vals = [v.eval(batch) for _, v in self.whens]
        default = (self.default.eval(batch) if self.default is not None
                   else np.zeros(len(batch)))
        return np.select(conds, vals, default)

    def children(self):
        return tuple(e for pair in self.whens for e in pair) + (
            (self.default,) if self.default is not None else ())

    def _rewrite_children(self, mapping):
        return Case(
            tuple((c.rewrite(mapping), v.rewrite(mapping))
                  for c, v in self.whens),
            self.default.rewrite(mapping) if self.default is not None
            else None)


def _scalar_fn(name: str):
    return {
        "ABS": np.abs,
        "FLOOR": np.floor,
        "CEIL": np.ceil,
        "CEILING": np.ceil,
        "SQRT": np.sqrt,
        "LN": np.log,
        "EXP": np.exp,
        "LOWER": lambda a: np.array([s.lower() for s in a], dtype=object),
        "UPPER": lambda a: np.array([s.upper() for s in a], dtype=object),
        "CHAR_LENGTH": lambda a: np.array([len(s) for s in a], dtype=np.int64),
    }.get(name)


@dataclasses.dataclass(frozen=True)
class ScalarFunc(Expr):
    name: str
    args: Tuple[Expr, ...]

    def eval(self, batch):
        if self.name == "MOD":
            return np.mod(self.args[0].eval(batch), self.args[1].eval(batch))
        if self.name == "POWER":
            return np.power(self.args[0].eval(batch), self.args[1].eval(batch))
        if self.name == "CONCAT":
            parts = [self.args[0].eval(batch).astype(object)]
            for a in self.args[1:]:
                parts.append(a.eval(batch).astype(object))
            out = parts[0]
            for p in parts[1:]:
                out = np.array([str(x) + str(y) for x, y in zip(out, p)],
                               dtype=object)
            return out
        fn = _scalar_fn(self.name)
        if fn is None:
            raise ValueError(f"unknown scalar function {self.name}")
        return fn(self.args[0].eval(batch))

    def children(self):
        return self.args

    def output_name(self):
        return self.name.lower()

    def _rewrite_children(self, mapping):
        return ScalarFunc(self.name,
                          tuple(a.rewrite(mapping) for a in self.args))


_CAST_DTYPES = {
    "INT": np.int32, "INTEGER": np.int32, "BIGINT": np.int64,
    "FLOAT": np.float32, "DOUBLE": np.float64, "REAL": np.float32,
    "SMALLINT": np.int16, "TINYINT": np.int8, "BOOLEAN": np.bool_,
    "VARCHAR": object, "STRING": object, "CHAR": object,
}


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    operand: Expr
    type_name: str

    def eval(self, batch):
        v = self.operand.eval(batch)
        dt = _CAST_DTYPES[self.type_name]
        if dt is object:
            return np.array([str(x) for x in v], dtype=object)
        return v.astype(dt)

    def children(self):
        return (self.operand,)

    def output_name(self):
        return self.operand.output_name()

    def _rewrite_children(self, mapping):
        return Cast(self.operand.rewrite(mapping), self.type_name)


AGG_NAMES = ("COUNT", "SUM", "MIN", "MAX", "AVG")


@dataclasses.dataclass(frozen=True)
class AggCall(Expr):
    """Aggregate marker — never evaluated directly; the planner maps it to a
    device AggregateFunction and replaces it with a Column over the result."""

    func: str                      # one of AGG_NAMES
    arg: Optional[Expr] = None     # None for COUNT(*)
    distinct: bool = False

    def eval(self, batch):
        raise RuntimeError(
            f"{self.func}(...) must be planned as an aggregation, "
            "not evaluated row-wise")

    def children(self):
        return (self.arg,) if self.arg is not None else ()

    def output_name(self):
        if self.arg is None:
            return self.func.lower()
        return f"{self.func.lower()}_{self.arg.output_name()}"


@dataclasses.dataclass(frozen=True)
class OverCall(Expr):
    """ROW_NUMBER()/RANK() OVER (PARTITION BY ... ORDER BY ...) — planned as
    a RankOperator (reference: flink-table-runtime rank operators)."""

    func: str                          # ROW_NUMBER | RANK
    partition_by: Tuple[Expr, ...]
    order_by: Tuple[Tuple[Expr, bool], ...]  # (expr, descending)

    def eval(self, batch):
        raise RuntimeError("OVER window must be planned, not evaluated")

    def children(self):
        return self.partition_by + tuple(e for e, _ in self.order_by)

    def output_name(self):
        return self.func.lower()


@dataclasses.dataclass(frozen=True)
class OverAgg(Expr):
    """agg(x) OVER (PARTITION BY k ORDER BY rowtime ROWS|RANGE BETWEEN n
    PRECEDING AND CURRENT ROW) — planned as an OverAggOperator
    (reference: StreamExecOverAggregate -> RowTimeRowsBoundedPrecedingFunction
    and friends in flink-table-runtime/.../over/)."""

    func: str                          # one of AGG_NAMES
    arg: Optional[Expr]                # None for COUNT(*)
    partition_by: Tuple[Expr, ...]
    order_by: Tuple[Tuple[Expr, bool], ...]  # (expr, descending)
    mode: str = "ROWS"                 # ROWS | RANGE
    #: frame reach before the current row: row count (ROWS) or
    #: milliseconds (RANGE); None = UNBOUNDED PRECEDING
    preceding: Optional[int] = None

    def eval(self, batch):
        raise RuntimeError("OVER window must be planned, not evaluated")

    def children(self):
        out = tuple(self.partition_by) + tuple(
            e for e, _ in self.order_by)
        return out + ((self.arg,) if self.arg is not None else ())

    def aggregates(self):
        # an OVER aggregate is NOT a grouping aggregate — it adds a
        # column per input row (the planner routes it separately)
        return []

    def output_name(self):
        base = (self.func.lower() if self.arg is None
                else f"{self.func.lower()}_{self.arg.output_name()}")
        return f"{base}_over"


@dataclasses.dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str] = None

    @property
    def name(self) -> str:
        return self.alias or self.expr.output_name()
