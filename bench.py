"""Headline benchmark: Nexmark Q5 — hot items over a sliding window.

keyBy(auction) -> HOP(10 s size, 2 s slide) -> COUNT -> per-window arg-max,
on the synthetic Nexmark bid stream (flink_tpu/benchmarks/nexmark.py). Runs
the full framework path: DataStream API -> local executor -> native slot-map
index -> jitted scatter/gather kernels on the active JAX backend.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Diagnostics (fire latency percentiles, result counts) go to stderr.

Robustness: the TPU backend in this environment is tunneled and flaky —
init can hang or fail outright. The backend is therefore probed in a
SUBPROCESS with a hard timeout and retried with backoff; if it never comes
up, the benchmark falls back to CPU and still emits the JSON line (with an
"error" field naming the degradation) and exits 0. A missing perf number
is worse than a degraded one.

Baseline note (see BASELINE.md): the reference (Apache Flink, JVM) cannot be
built or executed in this zero-egress container and publishes no absolute
numbers in-repo. vs_baseline is computed against the documented proxy of
500_000 events/s/chip for Flink's RocksDB-backed windowed aggregation; the
>=10x target of BASELINE.json corresponds to vs_baseline >= 10.
"""

import json
import os
import subprocess
import sys
import time

PROXY_BASELINE_EVENTS_PER_S = 500_000.0

_PROBE_SCRIPT = r"""
import os, sys
from flink_tpu.platform import sync_platform
sync_platform()
import jax
devs = jax.devices()
import jax.numpy as jnp
x = jnp.ones((256, 256), dtype=jnp.bfloat16)
(x @ x).block_until_ready()
print(devs[0].platform)
"""


def probe_backend(timeouts=(45, 90, 180)) -> tuple:
    """Probe the default (TPU) backend in a subprocess with a hard timeout.

    Returns (ok, platform_or_error). A hanging or crashing init cannot take
    the benchmark process down with it.
    """
    if os.environ.get("BENCH_PROBE_TIMEOUTS"):
        timeouts = tuple(
            int(t) for t in
            os.environ["BENCH_PROBE_TIMEOUTS"].split(","))
    last_err = "no attempts made"
    for i, timeout_s in enumerate(timeouts):
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SCRIPT],
                capture_output=True, text=True, timeout=timeout_s,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            if proc.returncode == 0:
                platform = proc.stdout.strip().splitlines()[-1]
                print(f"# backend probe ok ({platform}) in "
                      f"{time.time() - t0:.1f}s", file=sys.stderr)
                return True, platform
            last_err = (proc.stderr or proc.stdout).strip().splitlines()
            last_err = last_err[-1] if last_err else f"rc={proc.returncode}"
        except subprocess.TimeoutExpired:
            last_err = f"backend init hang (> {timeout_s}s)"
        print(f"# backend probe attempt {i + 1} failed: {last_err}",
              file=sys.stderr)
        if i + 1 < len(timeouts):
            time.sleep(5 * (i + 1))  # backoff before retry
    return False, str(last_err)


def run(total_records: int, num_auctions: int = 100_000,
        batch_size: int = None, layout: str = "slots") -> dict:
    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.benchmarks.nexmark import BidSource, build_q5
    from flink_tpu.connectors.sinks import CollectSink

    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    if batch_size is None:
        # Platform-conditional defaults (swept 2026-07-30/31):
        # - TPU behind the tunnel (~64 ms RTT): 1M-row batches amortize
        #   the round trip (131k-row batches cap at ~0.9M ev/s, 1M-row
        #   at ~5.8M on the same chip); dispatch-ahead 8 hides the RTT.
        # - CPU: 64k-row batches + dispatch-ahead 1 measured BOTH the
        #   best throughput (3.28M ev/s) and fire p50/p99 = 41/91 ms
        #   over 204 samples — deep pipelining only queues fires behind
        #   scatter work when the "device" is the same core.
        batch_size = int(os.environ.get(
            "BENCH_BATCH_SIZE", 1 << 20 if on_tpu else 1 << 16))
    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": batch_size,
        # headroom above the live (key x slice) footprint so ring/column
        # growth never interrupts the measured run
        "state.slot-table.capacity": 1 << 22,
        "state.window-layout": layout,
        # dispatch pipelining depth — the lever for a high-RTT device
        # link (the tunneled TPU): deeper hides the RTT per batch,
        # shallower keeps fire kernels from queueing behind scatters
        "execution.pipeline.max-dispatch-batches": int(
            os.environ.get("BENCH_DISPATCH_AHEAD", 8 if on_tpu else 1)),
    }))
    sink = CollectSink()
    # 100k events/s of event time -> a 2 s slide covers ~200k events, a 10 s
    # window ~1M; the default 40M records span 400 s of event time = 200 HOP
    # slide boundaries, so the fire-latency p99 is over >=200 fire samples
    # (one per watermark advance that closes windows) rather than the ~24
    # the old geometry produced.
    src = BidSource(total_records=total_records, num_auctions=num_auctions,
                    events_per_second_of_eventtime=100_000)
    build_q5(env, src, size_ms=10_000, slide_ms=2_000,
             device_top_k=16).sink_to(sink)
    t0 = time.perf_counter()
    result = env.execute("nexmark-q5-hot-items")
    elapsed = time.perf_counter() - t0
    return {
        "events_per_s": total_records / elapsed,
        "elapsed_s": elapsed,
        "results": len(sink.result()),
        "fire_latency_ms": result.metrics.get("window_fire_latency_ms"),
    }


def emit(value: float, error: str = None, extra: dict = None) -> None:
    line = {
        "metric": "nexmark_q5_hop_hot_items_events_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "events/s",
        "vs_baseline": round(value / PROXY_BASELINE_EVENTS_PER_S, 3),
    }
    if extra:
        line.update(extra)
    if error:
        line["error"] = error
    print(json.dumps(line))
    sys.stdout.flush()


def main():
    import warnings

    warnings.filterwarnings("ignore")
    error = None
    if os.environ.get("BENCH_SKIP_PROBE") != "1":
        ok, info = probe_backend()
        if not ok:
            error = f"tpu backend unavailable ({info}); measured on cpu"
            os.environ["JAX_PLATFORMS"] = "cpu"
        elif info not in ("tpu", "axon"):
            # probe "succeeded" but JAX silently fell back to another
            # platform — mark the degradation rather than publishing a
            # non-TPU number as a TPU one
            error = f"measured on {info} (no tpu device came up)"
    from flink_tpu.platform import sync_platform

    sync_platform()

    total = int(os.environ.get("BENCH_RECORDS", 40_000_000))
    # Measure BOTH window-state layouts and report the better one: the
    # pane layout removes the per-fire host->device slot matrix (designed
    # for the tunneled-TPU transfer cost), the slot layout is the measured
    # incumbent — the headline must never regress on an unmeasured layout.
    stats = None
    best_layout = None
    import jax as _jax

    # On CPU the pane layout is not competitive (measured 2026-07-31:
    # 185k ev/s vs slots' 3.28M — its dense per-fire reductions only pay
    # off when they delete host->device transfers); don't spend minutes
    # measuring it there.
    layouts = (("panes", "slots")
               if _jax.default_backend() not in ("cpu",) else ("slots",))
    for layout in layouts:
        try:
            # Warmup must cover the FIRE path too: at 100k events/s of
            # event time the first HOP window closes at 2 s, so the warmup
            # needs >200k records for the watermark to cross a window end
            # and compile the fire/merge kernels (at the production
            # num_auctions so the pad buckets match the measured run).
            run(total_records=1 << 21, num_auctions=100_000, layout=layout)
            # Steady-state: repeat the measured pass and take the MEDIAN
            # rep as the headline (best-of overstates sustained
            # throughput; the warm-up pass above already covers the
            # compile/cache-settling argument). Best and all reps stay
            # in the JSON as secondary fields — tunnel-throughput
            # variance across sessions remains visible there.
            reps = []
            for rep in range(max(int(os.environ.get("BENCH_REPS", 3)), 1)):
                r = run(total_records=total, layout=layout)
                print(f"# layout={layout} rep {rep}: "
                      f"{r['events_per_s']:.0f} events/s, "
                      f"fire_latency={r['fire_latency_ms']}",
                      file=sys.stderr)
                reps.append(r)
            by_rate = sorted(reps, key=lambda r: r["events_per_s"])
            s = by_rate[len(by_rate) // 2]  # median (upper-mid for even)
            s["rep_events_per_s"] = [round(r["events_per_s"], 1)
                                     for r in reps]
            s["best_events_per_s"] = round(
                by_rate[-1]["events_per_s"], 1)
            if stats is None or s["events_per_s"] > stats["events_per_s"]:
                stats, best_layout = s, layout
        except Exception as e:  # degraded: keep trying the other layout
            print(f"# layout={layout} failed: {e!r}", file=sys.stderr)
    if stats is None:
        try:
            stats = run(total_records=1 << 19)  # smaller degraded run
            best_layout = "slots"
            error = ((error + "; " if error else "")
                     + "full runs failed, value from reduced run")
        except Exception as e2:
            print(f"# degraded run also failed: {e2!r}", file=sys.stderr)
            emit(0.0, (error + "; " if error else "")
                 + f"benchmark failed: {e2!r}")
            return
    print(f"# q5 best layout={best_layout}: {stats['results']} winner "
          f"rows, fire_latency={stats['fire_latency_ms']}", file=sys.stderr)
    emit(stats["events_per_s"], error,
         extra={k: stats[k]
                for k in ("rep_events_per_s", "best_events_per_s")
                if k in stats})


if __name__ == "__main__":
    main()
