"""Headline benchmark: Nexmark Q5-style hot-items over a sliding window.

keyBy(auction) -> HOP(10 s size, 2 s slide) -> COUNT, skewed keys — the
BASELINE.json row-2 config. Runs the full framework path (DataStream API ->
local executor -> slot-table scatter kernels on the active JAX backend).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline note (see BASELINE.md): the reference (Apache Flink, JVM) cannot be
built or executed in this zero-egress container, and the reference repo
publishes no absolute numbers. vs_baseline is therefore computed against the
documented proxy of 500_000 events/s/chip for Flink's RocksDB-backed windowed
aggregation (a generous per-machine figure relative to typical published
Nexmark q5 RocksDB results); the ≥10x target of BASELINE.json means
vs_baseline >= 10.
"""

import json
import os
import time

from flink_tpu.platform import sync_platform as _sync_platform

PROXY_BASELINE_EVENTS_PER_S = 500_000.0


def run(total_records: int = 8_000_000, num_keys: int = 100_000,
        batch_size: int = 1 << 17) -> dict:
    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.runtime.watermarks import WatermarkStrategy
    from flink_tpu.windowing.assigners import SlidingEventTimeWindows

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": batch_size,
        "state.slot-table.capacity": 1 << 20,
    }))
    sink = CollectSink()
    # 200k events per second of event time -> each 2 s slide covers ~400k
    # events and a 10 s window ~2M, sized against the 1<<20 slot capacity
    src = DataGenSource(total_records=total_records, num_keys=num_keys,
                        events_per_second_of_eventtime=200_000, skew=0.2)
    stream = (
        env.from_source(src, WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key")
        .window(SlidingEventTimeWindows.of(10_000, 2_000))
        .count()
    )
    stream.sink_to(sink)
    # grab the operator to read fire latencies
    t0 = time.perf_counter()
    result = env.execute("nexmark-q5-hot-items")
    elapsed = time.perf_counter() - t0

    events_per_s = total_records / elapsed
    return {
        "events_per_s": events_per_s,
        "elapsed_s": elapsed,
        "results": len(sink.result()),
        "fire_latency_ms": result.metrics.get("window_fire_latency_ms"),
    }


def main():
    _sync_platform()
    import warnings

    warnings.filterwarnings("ignore")
    total = int(os.environ.get("BENCH_RECORDS", 8_000_000))
    # warmup (compile cache)
    run(total_records=1 << 18, num_keys=10_000)
    stats = run(total_records=total)
    value = stats["events_per_s"]
    print(json.dumps({
        "metric": "nexmark_q5_hop_hot_items_events_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "events/s",
        "vs_baseline": round(value / PROXY_BASELINE_EVENTS_PER_S, 3),
    }))


if __name__ == "__main__":
    main()
