"""Headline benchmark: Nexmark Q5 — hot items over a sliding window.

keyBy(auction) -> HOP(10 s size, 2 s slide) -> COUNT -> per-window arg-max,
on the synthetic Nexmark bid stream (flink_tpu/benchmarks/nexmark.py). Runs
the full framework path: DataStream API -> local executor -> native slot-map
index -> jitted scatter/gather kernels on the active JAX backend.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Diagnostics (fire latency percentiles, result counts) go to stderr.

Baseline note (see BASELINE.md): the reference (Apache Flink, JVM) cannot be
built or executed in this zero-egress container and publishes no absolute
numbers in-repo. vs_baseline is computed against the documented proxy of
500_000 events/s/chip for Flink's RocksDB-backed windowed aggregation; the
>=10x target of BASELINE.json corresponds to vs_baseline >= 10.
"""

import json
import os
import sys
import time

from flink_tpu.platform import sync_platform as _sync_platform

PROXY_BASELINE_EVENTS_PER_S = 500_000.0


def run(total_records: int, num_auctions: int = 100_000,
        batch_size: int = 1 << 17) -> dict:
    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.benchmarks.nexmark import BidSource, build_q5
    from flink_tpu.connectors.sinks import CollectSink

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": batch_size,
        "state.slot-table.capacity": 1 << 20,
    }))
    sink = CollectSink()
    # 200k events/s of event time -> a 2 s slide covers ~400k events, a 10 s
    # window ~2M, sized against the 1<<20 slot capacity
    src = BidSource(total_records=total_records, num_auctions=num_auctions,
                    events_per_second_of_eventtime=200_000)
    build_q5(env, src, size_ms=10_000, slide_ms=2_000).sink_to(sink)
    t0 = time.perf_counter()
    result = env.execute("nexmark-q5-hot-items")
    elapsed = time.perf_counter() - t0
    return {
        "events_per_s": total_records / elapsed,
        "elapsed_s": elapsed,
        "results": len(sink.result()),
        "fire_latency_ms": result.metrics.get("window_fire_latency_ms"),
    }


def main():
    _sync_platform()
    import warnings

    warnings.filterwarnings("ignore")
    total = int(os.environ.get("BENCH_RECORDS", 8_000_000))
    run(total_records=1 << 18, num_auctions=10_000)  # warmup/compile
    stats = run(total_records=total)
    print(f"# q5: {stats['results']} winner rows, "
          f"fire_latency={stats['fire_latency_ms']}", file=sys.stderr)
    value = stats["events_per_s"]
    print(json.dumps({
        "metric": "nexmark_q5_hop_hot_items_events_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "events/s",
        "vs_baseline": round(value / PROXY_BASELINE_EVENTS_PER_S, 3),
    }))


if __name__ == "__main__":
    main()
