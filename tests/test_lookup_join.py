"""Lookup joins (dimension-table enrichment).

reference: LookupTableSource / LookupFunction + StreamExecLookupJoin ->
LookupJoinRunner, with the FLIP-221 lookup cache."""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.lookup import (
    LookupJoinOperator,
    TableLookupFunction,
)
from flink_tpu.core.records import RecordBatch
from flink_tpu.table.environment import StreamTableEnvironment


def _dim():
    return TableLookupFunction(
        [{"cur": 1, "name": "EUR", "factor": 1.1},
         {"cur": 2, "name": "GBP", "factor": 1.3}],
        key_column="cur")


class _Ctx:
    max_parallelism = 128
    operator_index = 0


class TestOperator:
    def _batch(self, curs):
        return RecordBatch.from_pydict(
            {"cur": np.asarray(curs, dtype=np.int64),
             "amount": np.arange(len(curs), dtype=np.float64)})

    def test_inner_drops_misses(self):
        op = LookupJoinOperator(_dim(), "cur")
        op.open(_Ctx())
        out = op.process_batch(self._batch([1, 9, 2]))[0]
        assert list(out["name"]) == ["EUR", "GBP"]
        assert out["amount"].tolist() == [0.0, 2.0]

    def test_left_outer_pads_misses(self):
        op = LookupJoinOperator(_dim(), "cur", left_outer=True)
        op.open(_Ctx())
        out = op.process_batch(self._batch([1, 9]))[0]
        assert len(out) == 2
        assert out["amount"].tolist() == [0.0, 1.0]
        assert list(out["name"])[0] == "EUR"

    def test_declared_schema_stable_across_all_miss_batches(self):
        """With declared columns, an all-miss LEFT batch still emits
        every right column (one schema across batches)."""
        op = LookupJoinOperator(_dim(), "cur",
                                right_columns=["cur", "name", "factor"],
                                left_outer=True)
        op.open(_Ctx())
        hit = op.process_batch(self._batch([1]))[0]
        miss = op.process_batch(self._batch([9]))[0]
        assert set(hit.names()) == set(miss.names())
        assert "name" in miss.names() and "factor" in miss.names()

    def test_cache_bounds_lookup_calls(self):
        op = LookupJoinOperator(_dim(), "cur", cache_size=10)
        op.open(_Ctx())
        op.process_batch(self._batch([1, 2, 1, 2]))
        assert op.lookups == 1
        op.process_batch(self._batch([2, 1]))
        assert op.lookups == 1  # all cached (incl. per-batch dedup)
        op.process_batch(self._batch([9]))  # miss -> negative cached
        assert op.lookups == 2
        op.process_batch(self._batch([9]))
        assert op.lookups == 2

    def test_cache_off_by_default_sees_live_updates(self):
        """FLIP-221: caching is opt-in. A dimension row inserted after
        the first (missed) access must be observed (advisor r4, low)."""
        fn = _dim()
        op = LookupJoinOperator(fn, "cur",
                                right_columns=["cur", "name", "factor"],
                                left_outer=True)
        op.open(_Ctx())
        out = op.process_batch(self._batch([9]))[0]
        assert str(out["name"][0]) in ("nan", "None")  # miss padded
        fn._by_key[9] = {"cur": 9, "name": "JPY", "factor": 0.007}
        out = op.process_batch(self._batch([9]))[0]
        assert list(out["name"]) == ["JPY"]  # no stale negative cache

    def test_cache_ttl_expires_entries(self, monkeypatch):
        import time as _time

        clock = [0.0]
        monkeypatch.setattr(_time, "monotonic", lambda: clock[0])
        fn = _dim()
        op = LookupJoinOperator(fn, "cur", cache_size=10,
                                cache_ttl_ms=1000)
        op.open(_Ctx())
        op.process_batch(self._batch([1]))
        assert op.lookups == 1
        clock[0] = 0.5  # within TTL: served from cache
        op.process_batch(self._batch([1]))
        assert op.lookups == 1
        fn._by_key[1] = {"cur": 1, "name": "EUR2", "factor": 2.0}
        clock[0] = 1.5  # past TTL: refetched, update observed
        out = op.process_batch(self._batch([1]))[0]
        assert op.lookups == 2
        assert list(out["name"]) == ["EUR2"]


class TestLookupJoinSQL:
    def _env(self):
        from flink_tpu.connectors.kafka import FakeBroker

        broker = FakeBroker.get("default")
        broker.create_topic("lkp_orders", 1)
        ts = np.asarray([1000, 2000, 3000], dtype=np.int64)
        broker.append("lkp_orders", 0, RecordBatch.from_pydict(
            {"cur": np.asarray([1, 9, 2], dtype=np.int64),
             "amount": np.asarray([10.0, 20.0, 30.0]),
             "ts": ts}, timestamps=ts))
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 2}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE lkp_orders (cur BIGINT, amount DOUBLE, "
            "ts BIGINT, WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='lkp_orders')")
        tenv.create_lookup_table("rates_dim", _dim(),
                                 ["cur", "name", "factor"])
        return tenv

    def test_enrichment_query(self):
        tenv = self._env()
        rows = tenv.execute_sql("""
            SELECT o.amount * r.factor AS conv, r.name
            FROM lkp_orders AS o
            JOIN rates_dim FOR SYSTEM_TIME AS OF o.ts AS r
            ON o.cur = r.cur
        """).collect()
        got = sorted((round(r["conv"], 2), r["name"]) for r in rows)
        assert got == [(11.0, "EUR"), (39.0, "GBP")]

    def test_wrong_key_column_rejected(self):
        from flink_tpu.table.environment import PlanError

        tenv = self._env()
        with pytest.raises(PlanError, match="keyed by"):
            tenv.execute_sql("""
                SELECT o.amount FROM lkp_orders AS o
                JOIN rates_dim FOR SYSTEM_TIME AS OF o.ts AS r
                ON o.cur = r.name
            """)
