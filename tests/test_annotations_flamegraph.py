"""API stability contract enforcement (the reference's flink-annotations +
ArchUnit rules) and flame-graph sampling (VertexFlameGraph +
JobVertexFlameGraphHandler)."""

import threading
import time

import pytest

from flink_tpu.core.annotations import (
    INTERNAL,
    PUBLIC,
    PUBLIC_EVOLVING,
    stability_of,
)


class TestApiAnnotations:
    def test_every_top_level_export_is_public(self):
        """The ArchUnit role: everything exported from the package root
        must carry a public/public-evolving stability marker."""
        import flink_tpu

        unmarked = []
        for name in flink_tpu.__all__:
            obj = getattr(flink_tpu, name)
            if not isinstance(obj, type):
                continue  # __version__ etc.
            if stability_of(obj) not in (PUBLIC, PUBLIC_EVOLVING):
                unmarked.append(name)
        assert not unmarked, (
            f"top-level exports without @public/@public_evolving: "
            f"{unmarked}")

    def test_windowing_and_ml_surfaces_are_marked(self):
        import flink_tpu.ml as ml
        import flink_tpu.windowing as windowing

        for pkg in (windowing, ml):
            for name in pkg.__all__:
                obj = getattr(pkg, name)
                if isinstance(obj, type) and "Operator" not in name:
                    assert stability_of(obj) in (PUBLIC, PUBLIC_EVOLVING), \
                        f"{pkg.__name__}.{name}"

    def test_executors_are_internal(self):
        from flink_tpu.cluster.local_executor import LocalExecutor
        from flink_tpu.cluster.stage_executor import StageParallelExecutor
        from flink_tpu.state.slot_table import SlotTable

        for cls in (LocalExecutor, StageParallelExecutor, SlotTable):
            assert stability_of(cls) == INTERNAL, cls

    def test_internals_not_exported_from_root(self):
        import flink_tpu

        for name in flink_tpu.__all__:
            obj = getattr(flink_tpu, name)
            if isinstance(obj, type):
                assert stability_of(obj) != INTERNAL, name


class TestFlameGraph:
    def test_sampling_captures_named_threads(self):
        from flink_tpu.metrics.flamegraph import sample_flame_graph

        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(range(1000))

        t = threading.Thread(target=busy, name="task-flametest",
                             daemon=True)
        t.start()
        try:
            fg = sample_flame_graph(duration_ms=120, interval_ms=10,
                                    thread_name_prefixes=["task-"])
            assert fg["samples"] > 0
            root = fg["root"]
            names = [c["name"] for c in root["children"]]
            assert "task-flametest" in names
            thread_node = next(c for c in root["children"]
                               if c["name"] == "task-flametest")
            # the busy loop's frame appears somewhere in the folded stacks
            def frames(node):
                yield node["name"]
                for c in node["children"]:
                    yield from frames(c)

            assert any("busy" in f for f in frames(thread_node))
        finally:
            stop.set()

    def test_rest_flamegraph_endpoint(self):
        import json
        import urllib.request

        from flink_tpu import Configuration
        from flink_tpu.cluster.minicluster import MiniCluster

        cluster = MiniCluster(Configuration({
            "cluster.task-executors": 1, "rest.port": 0}))
        try:
            url = (f"http://127.0.0.1:{cluster.rest_port}"
                   f"/flamegraph?duration_ms=80&all=1")
            with urllib.request.urlopen(url, timeout=30) as resp:
                fg = json.loads(resp.read())
            assert "root" in fg and fg["samples"] >= 0
            assert "endTimestamp" in fg
        finally:
            cluster.shutdown()


class TestDashboard:
    def test_dashboard_html_served_at_ui(self):
        import urllib.request

        from flink_tpu import Configuration
        from flink_tpu.cluster.minicluster import MiniCluster

        cluster = MiniCluster(Configuration({
            "cluster.task-executors": 1, "rest.port": 0}))
        try:
            base = f"http://127.0.0.1:{cluster.rest_port}"
            with urllib.request.urlopen(f"{base}/ui", timeout=10) as resp:
                assert "text/html" in resp.headers["Content-Type"]
                html = resp.read().decode()
            assert "flink_tpu dashboard" in html
            assert "/ui/app.js" in html  # the SPA shell loads the app
            # the app and stylesheet serve with correct types
            with urllib.request.urlopen(f"{base}/ui/app.js",
                                        timeout=10) as resp:
                assert "javascript" in resp.headers["Content-Type"]
                js = resp.read().decode()
            assert "/taskexecutors" in js  # renders from the JSON surface
            assert "flamegraph" in js
            with urllib.request.urlopen(f"{base}/ui/style.css",
                                        timeout=10) as resp:
                assert "text/css" in resp.headers["Content-Type"]
            # path traversal / hidden files are rejected even with an
            # allowed extension (pins the guard, not the type filter)
            import urllib.error

            for probe in ("/ui/..%2Fweb%2Fapp.js", "/ui/.hidden.js",
                          "/ui/..%2Frest.py"):
                try:
                    urllib.request.urlopen(base + probe, timeout=10)
                    assert False, f"{probe} should 404"
                except urllib.error.HTTPError as e:
                    assert e.code == 404
            # "/" still serves the overview JSON (API compat)
            with urllib.request.urlopen(f"{base}/", timeout=10) as resp:
                assert "application/json" in resp.headers["Content-Type"]
            # the JSON surface itself is untouched
            import json

            with urllib.request.urlopen(f"{base}/overview",
                                        timeout=10) as resp:
                assert "application/json" in resp.headers["Content-Type"]
                json.loads(resp.read())
        finally:
            cluster.shutdown()
