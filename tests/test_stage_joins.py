"""Two-input keyed stages: joins running subtask-parallel.

reference: DefaultExecutionGraph runs multi-input vertices at any
parallelism; barrier alignment spans all input channels of both exchanges
(SingleCheckpointBarrierHandler). Here: two sources hash-exchange into a
two-input keyed operator expanded over N keyed subtasks.
"""

import numpy as np
import pytest

from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.config import Configuration
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _env(stage_par, source_par=1, extra=None):
    conf = {
        "execution.micro-batch.size": 1000,
        "execution.stage-parallelism": stage_par,
        "execution.source-parallelism": source_par,
    }
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def _window_join_pipeline(env, sink, total=5_000, keys=60,
                          fail_after=None, throttle_ms=0):
    a = env.from_source(
        DataGenSource(total_records=total, num_keys=keys,
                      events_per_second_of_eventtime=10_000, seed=3),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
    b = env.from_source(
        DataGenSource(total_records=total // 2, num_keys=keys,
                      events_per_second_of_eventtime=5_000, seed=4),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
    if throttle_ms:
        import time as _time

        def slow(batch, _ms=throttle_ms):
            _time.sleep(_ms / 1000.0)
            return batch

        a = a.map(slow, name="throttle")
    if fail_after is not None:
        from tests.test_checkpointing import FailingMap

        a = a.map(FailingMap(fail_after), name="failmap")
    (a.join(b).where("key").equal_to("key")
     .window(TumblingEventTimeWindows.of(1000))
     .apply(name="stage_join").sink_to(sink))


def _join_rows(sink):
    out = {}
    for r in sink.rows():
        k = (r["key"], r["window_start"], r["window_end"],
             round(r["value_l"], 4), round(r["value_r"], 4))
        out[k] = out.get(k, 0) + 1
    return out


def _interval_join_pipeline(env, sink, total=3_000, keys=40):
    a = env.from_source(
        DataGenSource(total_records=total, num_keys=keys,
                      events_per_second_of_eventtime=10_000, seed=5),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
    b = env.from_source(
        DataGenSource(total_records=total, num_keys=keys,
                      events_per_second_of_eventtime=10_000, seed=6),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
    (a.key_by("key").interval_join(b.key_by("key"))
     .between(-100, 100).sink_to(sink))


class TestTwoInputStagePlan:
    def test_join_graph_plans_two_inputs(self):
        from flink_tpu.cluster.stage_executor import plan_stages

        env = _env(2)
        sink = CollectSink()
        _window_join_pipeline(env, sink, total=100, keys=5)
        plan = plan_stages(env.get_stream_graph())
        assert len(plan.inputs) == 2
        assert plan.inputs[0].key_field == "key"
        assert plan.inputs[1].key_field == "key"
        assert plan.keyed_chain[-1].kind == "sink"


class TestStageParallelJoins:
    def _single_slot(self, builder, **kw):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000}))
        sink = CollectSink()
        builder(env, sink, **kw)
        env.execute("single")
        return sink

    def test_window_join_matches_single_slot(self):
        expected = _join_rows(self._single_slot(_window_join_pipeline))
        env = _env(4, source_par=2)
        sink = CollectSink()
        _window_join_pipeline(env, sink)
        result = env.execute("stage-join")
        assert result.metrics["stage_parallelism"] == 4
        got = _join_rows(sink)
        assert len(expected) > 0
        assert got == expected

    def test_interval_join_matches_single_slot(self):
        def rows(sink):
            out = {}
            for r in sink.rows():
                # the shared field name comes out suffixed on both sides
                k = (r["key_l"], round(r["value_l"], 4),
                     round(r["value_r"], 4))
                out[k] = out.get(k, 0) + 1
            return out

        env0 = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000}))
        s0 = CollectSink()
        _interval_join_pipeline(env0, s0)
        env0.execute("single")
        env = _env(3, source_par=2)
        sink = CollectSink()
        _interval_join_pipeline(env, sink)
        env.execute("stage-ijoin")
        assert len(s0.rows()) > 0
        assert rows(sink) == rows(s0)

    def test_crash_restore_matches_clean_run(self, tmp_path):
        ckpt = str(tmp_path / "ckpts")
        expected = _join_rows(self._single_slot(_window_join_pipeline))

        extra = {
            "state.checkpoints.dir": ckpt,
            "execution.checkpointing.every-n-source-batches": 1,
            "execution.micro-batch.size": 100,
        }
        env = _env(4, source_par=2, extra=extra)
        crash_sink = CollectSink()
        # fail_after counts RECORDS (per subtask instance); the throttle
        # keeps sources alive long enough for checkpoints to land before
        # the crash (the loop triggers between source polls)
        _window_join_pipeline(env, crash_sink, fail_after=1500,
                              throttle_ms=5)
        with pytest.raises(RuntimeError, match="injected"):
            env.execute("crashing")

        from flink_tpu.checkpoint.storage import CheckpointStorage

        assert CheckpointStorage(ckpt).latest_checkpoint_id() is not None

        # the restored graph must match the snapshot's topology: same
        # nodes (throttle/failmap as no-ops), same names, same order
        env2 = _env(4, source_par=2, extra=extra)
        sink2 = CollectSink()
        _window_join_pipeline(env2, sink2, fail_after=10**9,
                              throttle_ms=0.001)
        env2.execute("restored", restore_from=ckpt)
        got = _join_rows(sink2)

        # exactly-once at window granularity: a window either re-fires
        # completely in the restored run (rows identical to clean) or was
        # fully emitted before the crash — the union covers every window
        def windows(d):
            return {(k[0], k[1], k[2]) for k in d}

        for k, c in got.items():
            assert k in expected, f"unexpected join row {k}"
            assert c == expected[k], (k, c, expected[k])
        crashed = _join_rows(crash_sink)
        got_windows = windows(got)
        covered = got_windows | windows(crashed)
        assert windows(expected) <= covered, \
            "windows lost across crash + restore"
        # restored-run windows are complete: every expected row of a
        # restored window is present with the right multiplicity
        for k, c in expected.items():
            if (k[0], k[1], k[2]) in got_windows:
                assert got.get(k) == c, (k, got.get(k), c)
