"""Kafka-shaped partitioned source: partitions as splits, offsets in
checkpoints, rebalance on parallelism change, SQL DDL.

reference: flink-connector-base SourceReaderBase split-reader stack +
flink-connector-kafka (partition discovery, offset checkpointing);
BASELINE row 4 — SQL GROUP BY HOP over a partitioned source with
exactly-once restore.
"""

import numpy as np
import pytest

from flink_tpu.connectors.kafka import (
    FakeBroker,
    KafkaPartitionCoordinator,
    KafkaSink,
    KafkaSource,
)
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.core.config import Configuration
from flink_tpu.core.records import RecordBatch
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


@pytest.fixture(autouse=True)
def fresh_broker():
    FakeBroker.reset()
    yield
    FakeBroker.reset()


def _produce(topic, n=5000, keys=50, parts=4, broker=None, start_i=0):
    broker = broker or FakeBroker.get()
    rows = [{"key": i % keys, "value": float(i % 97) / 7.0,
             "ts": (start_i + i) * 2}
            for i in range(n)]
    broker.produce_rows(topic, rows, partition_by="key",
                        num_partitions=parts, timestamp_field="ts")
    return rows


def _oracle_hop(rows, size, slide):
    out = {}
    for r in rows:
        ts = r["ts"]
        first = ts - (ts % slide) + slide
        for w in range(first, ts + size + 1, slide):
            if w - size <= ts < w:
                k = (r["key"], w)
                out[k] = out.get(k, 0.0) + r["value"]
    return out


class TestBroker:
    def test_append_fetch_offsets(self):
        b = FakeBroker.get()
        b.create_topic("t", 2)
        base0 = b.append("t", 0, RecordBatch.from_pydict(
            {"x": np.arange(5)}))
        base1 = b.append("t", 0, RecordBatch.from_pydict(
            {"x": np.arange(5, 9)}))
        assert (base0, base1) == (0, 5)
        batch, nxt = b.fetch("t", 0, 2, 4)
        assert nxt == 6
        np.testing.assert_array_equal(batch["x"], [2, 3, 4, 5])
        batch, nxt = b.fetch("t", 0, 9, 10)
        assert batch is None and nxt == 9
        assert b.end_offset("t", 0) == 9


class TestKafkaSource:
    def test_reads_all_partitions(self):
        rows = _produce("t1", n=3000, parts=4)
        src = KafkaSource("t1")
        src.open(0, 1)
        got = 0
        while True:
            b = src.poll_batch(500)
            if b is None:
                break
            got += len(b)
        assert got == len(rows)

    def test_partition_rebalance_on_parallelism_change(self):
        _produce("t2", n=100, parts=6)
        owned = {}
        for P in (2, 3):
            owned[P] = []
            for sub in range(P):
                s = KafkaSource("t2")
                s.open(sub, P)
                owned[P].append(sorted(
                    st.split.split_id for st in s._states.values()))
        # coverage is exact and disjoint at every parallelism
        for P, per_sub in owned.items():
            flat = [sid for sids in per_sub for sid in sids]
            assert sorted(flat) == sorted(f"t2-{p}" for p in range(6))
        # deterministic modulo: partition p -> subtask p % P
        assert owned[2][0] == ["t2-0", "t2-2", "t2-4"]
        assert owned[3][1] == ["t2-1", "t2-4"]

    def test_unbounded_discovers_new_partitions(self):
        b = FakeBroker.get()
        _produce("t3", n=200, parts=2)
        src = KafkaSource("t3", bounded=False)
        src.open(0, 1)
        got = 0
        for _ in range(50):
            batch = src.poll_batch(100)
            if batch is not None:
                got += len(batch)
            if got >= 200:
                break
        assert got == 200
        # partition expansion: new partition picked up by re-discovery
        b.add_partitions("t3", 3)
        b.append("t3", 2, RecordBatch.from_pydict(
            {"key": np.arange(7), "value": np.ones(7), "ts": np.arange(7)}))
        extra = 0
        for _ in range(50):
            batch = src.poll_batch(100)
            if batch is not None:
                extra += len(batch)
            if extra >= 7:
                break
        assert extra == 7

    def test_offsets_survive_snapshot_restore(self):
        rows = _produce("t4", n=2000, parts=3)
        src = KafkaSource("t4")
        src.open(0, 1)
        seen = []
        for _ in range(4):
            b = src.poll_batch(123)
            if b is not None and len(b):
                seen.extend(b["key"].tolist())
        pos = src.snapshot_position()
        # keep reading the original (post-snapshot records must be
        # re-read by the restored instance)
        restored = KafkaSource("t4")
        restored.open(0, 1)
        restored.restore_position(pos)
        rest = []
        while True:
            b = restored.poll_batch(321)
            if b is None:
                break
            rest.extend(b["key"].tolist())
        assert len(seen) + len(rest) == len(rows)


class TestKafkaPipeline:
    def test_windowed_sum_matches_oracle(self):
        rows = _produce("t5", n=6000, keys=40, parts=4)
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 777}))
        src = KafkaSource("t5", timestamp_field="ts")
        sink = CollectSink()
        env.from_source(src, src.watermark_strategy(0)) \
           .key_by("key").window(TumblingEventTimeWindows.of(1000)) \
           .sum("value").sink_to(sink)
        env.execute("kafka-window")
        oracle = {}
        for r in rows:
            k = (r["key"], (r["ts"] // 1000 + 1) * 1000)
            oracle[k] = oracle.get(k, 0.0) + r["value"]
        got = {(r["key"], r["window_end"]): r["sum_value"]
               for r in sink.rows()}
        assert set(got) == set(oracle)
        for k in oracle:
            assert got[k] == pytest.approx(oracle[k], rel=1e-4)

    def test_exactly_once_crash_restore(self, tmp_path):
        from tests.test_checkpointing import FailingMap

        rows = _produce("t6", n=8000, keys=60, parts=4)
        oracle = {}
        for r in rows:
            k = (r["key"], (r["ts"] // 1000 + 1) * 1000)
            oracle[k] = oracle.get(k, 0.0) + r["value"]

        conf = {"execution.micro-batch.size": 500,
                "state.checkpoints.dir": str(tmp_path / "ck"),
                "execution.checkpointing.every-n-source-batches": 3}

        def build(env, sink, fail_after):
            src = KafkaSource("t6", timestamp_field="ts")
            (env.from_source(src, src.watermark_strategy(0))
             .map(FailingMap(fail_after), name="failmap")
             .key_by("key").window(TumblingEventTimeWindows.of(1000))
             .sum("value").sink_to(sink))

        env = StreamExecutionEnvironment(Configuration(conf))
        s1 = CollectSink()
        build(env, s1, 4000)
        with pytest.raises(RuntimeError, match="injected"):
            env.execute("crashing")
        env2 = StreamExecutionEnvironment(Configuration(conf))
        s2 = CollectSink()
        build(env2, s2, 10**12)
        env2.execute("restored", restore_from=str(tmp_path / "ck"))
        got = {}
        for r in s1.rows() + s2.rows():
            got[(r["key"], r["window_end"])] = r["sum_value"]
        assert set(got) == set(oracle)
        for k in oracle:
            assert got[k] == pytest.approx(oracle[k], rel=1e-4), k

    def test_kafka_sink_roundtrip(self):
        _produce("t7", n=1000, keys=10, parts=2)
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 300}))
        src = KafkaSource("t7", timestamp_field="ts")
        env.from_source(src, src.watermark_strategy(0)) \
           .sink_to(KafkaSink("t7-out", partition_by="key",
                              num_partitions=3))
        env.execute("copy")
        out = KafkaSource("t7-out")
        out.open(0, 1)
        n = 0
        while True:
            b = out.poll_batch(500)
            if b is None:
                break
            n += len(b)
        assert n == 1000


class TestKafkaSQL:
    def test_group_by_hop_over_kafka(self):
        """BASELINE row 4: SQL GROUP BY HOP over a partitioned source."""
        from flink_tpu.table.environment import StreamTableEnvironment

        rows = _produce("bids", n=6000, keys=30, parts=4)
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1024}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql("""
            CREATE TABLE bids (
                key BIGINT, value DOUBLE, ts BIGINT,
                WATERMARK FOR ts AS ts
            ) WITH ('connector' = 'kafka', 'topic' = 'bids')
        """)
        result = tenv.execute_sql("""
            SELECT key, window_end, SUM(value) AS total
            FROM TABLE(HOP(TABLE bids, DESCRIPTOR(ts),
                           INTERVAL '1' SECOND, INTERVAL '2' SECONDS))
            GROUP BY key, window_start, window_end
        """)
        oracle = _oracle_hop(rows, 2000, 1000)
        got = {}
        for r in result.collect():
            got[(r["key"], r["window_end"])] = r["total"]
        assert set(got) == set(oracle)
        for k in oracle:
            assert got[k] == pytest.approx(oracle[k], rel=1e-4), k

    def test_registered_table_replays_across_queries(self):
        """Two SELECTs over one registered kafka table must BOTH see the
        data: re-opening the source resets the enumerator and readers
        (regression: the second query discovered no splits)."""
        from flink_tpu.table.environment import StreamTableEnvironment

        _produce("replay_t", n=500, keys=5, parts=2)
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 128}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE replay_t (key BIGINT, value DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='replay_t')")
        first = tenv.execute_sql(
            "SELECT key, value FROM replay_t").collect()
        second = tenv.execute_sql(
            "SELECT key, value FROM replay_t").collect()
        assert len(first) == 500
        assert len(second) == 500

    def test_insert_into_kafka_table(self):
        from flink_tpu.table.environment import StreamTableEnvironment

        _produce("src8", n=2000, keys=20, parts=2)
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE src8 (key BIGINT, value DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='src8')")
        tenv.execute_sql(
            "CREATE TABLE out8 (key BIGINT, window_end BIGINT, "
            "total DOUBLE) WITH ('connector'='kafka', 'topic'='out8', "
            "'sink.partitions'='2', 'sink.partition-by'='key')")
        tenv.execute_sql("""
            INSERT INTO out8
            SELECT key, window_end, SUM(value) AS total
            FROM TABLE(TUMBLE(TABLE src8, DESCRIPTOR(ts),
                              INTERVAL '1' SECOND))
            GROUP BY key, window_start, window_end
        """)
        sink_read = KafkaSource("out8")
        sink_read.open(0, 1)
        n = 0
        while True:
            b = sink_read.poll_batch(1000)
            if b is None:
                break
            n += len(b)
        assert n > 0
