"""Multi-tenant session cluster: N jobs on one mesh (flink_tpu/tenancy/).

The tenancy claims, executed:
- job K+1 on a warm cluster compiles NOTHING (shared program cache,
  sentinel-verified);
- two jobs with IDENTICAL key spaces on one mesh are bit-identical to
  each running alone — windows, sessions, paged spill with forced
  eviction (cross-job state isolation is structural);
- a quota-exceeding job spills its OWN rows; its neighbor's resident
  rows never move (no cross-job reclaim);
- deficit-round-robin shares the loop (a hot job cannot starve the
  rest), and the serving plane coalesces concurrent lookups into
  device batches;
- crash mid-serving-burst restores each job INDEPENDENTLY and stays
  oracle-identical; an injected ``serving.lookup`` fault retries
  without corrupting engine state;
- arbiter-driven live rescale between jobs preserves oracle-identity.
"""

import queue as _q
import threading
import time

import numpy as np
import pytest

from flink_tpu.chaos import injection as chaos
from flink_tpu.chaos.harness import run_crash_restore_verify_multi
from flink_tpu.chaos.injection import FaultPlan, FaultRule
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.config import Configuration
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
from flink_tpu.parallel.sharded_windower import MeshWindowEngine
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.tenancy.arbiter import JobDemand, ShardArbiter
from flink_tpu.tenancy.fairness import DeficitRoundRobin
from flink_tpu.tenancy.program_cache import PROGRAM_CACHE
from flink_tpu.tenancy.quotas import QuotaLedger, TenantQuota
from flink_tpu.tenancy.serving import LookupCoalescer
from flink_tpu.tenancy.session_cluster import SessionCluster
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.assigners import TumblingEventTimeWindows
from tests.test_sessions import keyed_batch

GAP = 1_000
FINAL = 1 << 60


def _fired_dict(batches):
    out = {}
    for b in batches:
        for r in b.to_rows():
            out[(int(r["__key_id__"]), int(r["window_start"]),
                 int(r["window_end"]))] = float(r["sum_v"])
    return out


def _drive_sessions(engine, seed=7, n_batches=12, batch=512, keys=300):
    """Deterministic stream; returns fired {(}key, start, end) -> sum}."""
    rng = np.random.default_rng(seed)
    fired = {}
    t = 0
    for i in range(n_batches):
        ks = rng.integers(0, keys, batch)
        vs = np.ones(batch, dtype=np.float32)
        ts = t + np.sort(rng.integers(0, 400, batch))
        engine.process_batch(keyed_batch(ks, vs, ts))
        fired.update(_fired_dict(engine.on_watermark(t - 2 * GAP)))
        t += 700  # < gap: sessions span batches; > 0: watermark advances
    fired.update(_fired_dict(engine.on_watermark(FINAL)))
    return fired


def _drive_windows(engine, seed=3, n_batches=10, batch=512, keys=200):
    rng = np.random.default_rng(seed)
    fired = {}
    for i in range(n_batches):
        ks = rng.integers(0, keys, batch)
        vs = np.ones(batch, dtype=np.float32)
        ts = i * 500 + np.sort(rng.integers(0, 500, batch))
        engine.process_batch(keyed_batch(ks, vs, ts))
        fired.update(_fired_dict(engine.on_watermark(i * 500 - 1000)))
    fired.update(_fired_dict(engine.on_watermark(FINAL)))
    return fired


# ------------------------------------------------------------ program cache


class TestSharedProgramCache:
    def test_second_job_zero_misses_and_zero_compiles(self):
        """Job B's engines on a warm mesh must reuse job A's compiled
        programs: zero cache misses attributed to B AND zero real XLA
        compiles (the sentinel counts backend compilations)."""
        from flink_tpu.observe import RecompileSentinel

        mesh = make_mesh(4)

        def make():
            return MeshSessionEngine(GAP, SumAggregate("v"), mesh,
                                     capacity_per_shard=2048,
                                     max_device_slots=2048)

        with PROGRAM_CACHE.job_scope("warm-a"):
            _drive_sessions(make())
        PROGRAM_CACHE.reset_stats()
        with PROGRAM_CACHE.job_scope("warm-b"):
            with RecompileSentinel(max_compiles=0,
                                   label="second job") as s:
                _drive_sessions(make())
        stats = PROGRAM_CACHE.stats_for("warm-b")
        assert stats["misses"] == 0 and stats["hits"] >= 1
        assert s.compiles == 0

    def test_cache_key_is_what_not_who(self):
        """Same (devices, layout) from different jobs -> one program
        family; a different layout is a genuine miss."""
        from flink_tpu.parallel.sharded_windower import build_mesh_steps
        from flink_tpu.windowing.aggregates import CountAggregate

        mesh = make_mesh(2)
        PROGRAM_CACHE.reset_stats()
        with PROGRAM_CACHE.job_scope("k-a"):
            a = build_mesh_steps(mesh, SumAggregate("v"))
        with PROGRAM_CACHE.job_scope("k-b"):
            b = build_mesh_steps(mesh, SumAggregate("v"))
            c = build_mesh_steps(mesh, CountAggregate())
        assert a is b and c is not a
        assert PROGRAM_CACHE.stats_for("k-b")["hits"] >= 1

    def test_build_does_not_stall_other_keys_and_retries_on_failure(self):
        """The builder runs OUTSIDE the cache lock behind a per-key
        once-latch: while one thread compiles, a hit on a DIFFERENT key
        proceeds; two racers on the SAME key cost one build; a failed
        build releases its latch so the next caller retries."""
        import threading as th

        from flink_tpu.tenancy.program_cache import SharedProgramCache

        cache = SharedProgramCache()
        in_build = th.Event()
        release = th.Event()
        builds = []

        def slow_builder():
            in_build.set()
            assert release.wait(10)
            builds.append(1)
            return "slow"

        cache.get_or_build("other", ("k2",), lambda: "fast")
        t = th.Thread(target=cache.get_or_build,
                      args=("kind", ("k1",), slow_builder))
        t.start()
        assert in_build.wait(10)
        # mid-build: an unrelated cached key answers without stalling
        assert cache.get_or_build("other", ("k2",),
                                  lambda: "never") == "fast"
        # a same-key racer waits for the latch, then hits
        racer_out = []
        r = th.Thread(target=lambda: racer_out.append(
            cache.get_or_build("kind", ("k1",), slow_builder)))
        r.start()
        release.set()
        t.join(10), r.join(10)
        assert racer_out == ["slow"] and builds == [1]  # ONE build
        # a failed build releases the latch; the next caller retries
        with pytest.raises(RuntimeError):
            cache.get_or_build("kind", ("boom",),
                               lambda: (_ for _ in ()).throw(
                                   RuntimeError("compile failed")))
        assert cache.get_or_build("kind", ("boom",),
                                  lambda: "recovered") == "recovered"


# ------------------------------------------------------- cross-job isolation


class TestCrossJobIsolation:
    def test_two_session_jobs_identical_keyspace_bit_identical(self):
        """Sessions + paged spill with forced eviction: jobs A and B run
        the same key space interleaved on one mesh; each must produce
        outputs bit-identical to a solo run."""
        mesh = make_mesh(2)
        KEYS, BATCH, ADV = 50_000, 1024, 300  # live set >> 2x1024 slots

        def make():
            return MeshSessionEngine(GAP, SumAggregate("v"), mesh,
                                     capacity_per_shard=1024,
                                     max_device_slots=1024)  # forces evict

        def drive_one(eng):
            rng = np.random.default_rng(7)
            fired = {}
            t = 0
            for _ in range(12):
                ks = rng.integers(0, KEYS, BATCH)
                vs = np.ones(BATCH, dtype=np.float32)
                ts = t + np.sort(rng.integers(0, 250, BATCH))
                eng.process_batch(keyed_batch(ks, vs, ts))
                fired.update(_fired_dict(eng.on_watermark(t - 2 * GAP)))
                t += ADV
            fired.update(_fired_dict(eng.on_watermark(FINAL)))
            return fired

        solo = drive_one(make())
        a, b = make(), make()
        rng_a = np.random.default_rng(7)
        rng_b = np.random.default_rng(7)
        fired_a, fired_b = {}, {}
        t = 0
        for _ in range(12):
            for eng, rng, fired in ((a, rng_a, fired_a),
                                    (b, rng_b, fired_b)):
                ks = rng.integers(0, KEYS, BATCH)
                vs = np.ones(BATCH, dtype=np.float32)
                ts = t + np.sort(rng.integers(0, 250, BATCH))
                eng.process_batch(keyed_batch(ks, vs, ts))
                fired.update(_fired_dict(eng.on_watermark(t - 2 * GAP)))
            t += ADV
        fired_a.update(_fired_dict(a.on_watermark(FINAL)))
        fired_b.update(_fired_dict(b.on_watermark(FINAL)))
        assert a.spill_counters()["rows_evicted"] > 0  # genuinely spilled
        assert fired_a == solo
        assert fired_b == solo

    def test_two_window_jobs_identical_keyspace_bit_identical(self):
        mesh = make_mesh(4)

        def make():
            return MeshWindowEngine(
                TumblingEventTimeWindows.of(1_000), SumAggregate("v"),
                mesh, capacity_per_shard=1024, max_device_slots=1024)

        solo = _drive_windows(make())
        a, b = make(), make()
        fired_a, fired_b = {}, {}
        rng_a, rng_b = (np.random.default_rng(3),
                        np.random.default_rng(3))
        for i in range(10):
            for eng, rng, fired in ((a, rng_a, fired_a),
                                    (b, rng_b, fired_b)):
                ks = rng.integers(0, 200, 512)
                vs = np.ones(512, dtype=np.float32)
                ts = i * 500 + np.sort(rng.integers(0, 500, 512))
                eng.process_batch(keyed_batch(ks, vs, ts))
                fired.update(_fired_dict(eng.on_watermark(i * 500 - 1000)))
        fired_a.update(_fired_dict(a.on_watermark(FINAL)))
        fired_b.update(_fired_dict(b.on_watermark(FINAL)))
        assert fired_a == solo and fired_b == solo

    def test_quota_exceeder_spills_own_rows_never_neighbors(self):
        """Job B blows past its resident-row quota; enforcement sheds
        B's rows into B's tier. A's resident rows and spill counters do
        not move, and B's subsequent fires are still exact."""
        mesh = make_mesh(2)

        def make(slots):
            return MeshSessionEngine(GAP, SumAggregate("v"), mesh,
                                     capacity_per_shard=4096,
                                     max_device_slots=slots)

        a, b = make(4096), make(4096)
        # job A: small steady state
        a.process_batch(keyed_batch([1, 2, 3], [1.0, 1.0, 1.0],
                                    [0, 10, 20]))
        a_resident = sum(a.shard_resident_rows())
        a_spill = dict(a.spill_counters())
        # job B floods far past its quota
        ks = np.arange(6000, dtype=np.int64)
        b.process_batch(keyed_batch(ks, np.ones(6000, np.float32),
                                    np.zeros(6000, np.int64)))
        ledger_b = QuotaLedger(job="b",
                               quota=TenantQuota(max_resident_rows=2048))
        ledger_b.bind([b])
        assert ledger_b.resident_rows() > 2048
        shed = ledger_b.enforce()
        assert shed > 0
        assert ledger_b.resident_rows() <= 2048
        assert ledger_b.quota_violations == 0
        # neighbor untouched: same resident rows, same spill traffic
        assert sum(a.shard_resident_rows()) == a_resident
        assert dict(a.spill_counters()) == a_spill
        # B still fires exactly (shed rows reload/fire from its tier)
        fired = _fired_dict(b.on_watermark(FINAL))
        assert len(fired) == 6000
        assert all(v == 1.0 for v in fired.values())

    def test_quota_without_spill_tier_counts_violation(self):
        mesh = make_mesh(2)
        eng = MeshSessionEngine(GAP, SumAggregate("v"), mesh,
                                capacity_per_shard=4096)  # no budget/tier
        eng.process_batch(keyed_batch(np.arange(3000),
                                      np.ones(3000, np.float32),
                                      np.zeros(3000, np.int64)))
        ledger = QuotaLedger(job="x",
                             quota=TenantQuota(max_resident_rows=1024))
        ledger.bind([eng])
        assert ledger.enforce() == 0
        assert ledger.quota_violations >= 1

    def test_quota_counts_single_device_engines(self):
        """Regression: bind() unwrapped operators to their engine, and
        single-device engines define no shard_resident_rows — the quota
        silently became a no-op (resident 0 forever, never enforced,
        never violated). The OPERATOR carries the single-device
        fallback; bind must keep it."""
        from flink_tpu.core.records import (
            KEY_ID_FIELD,
            TIMESTAMP_FIELD,
            RecordBatch,
        )
        from flink_tpu.runtime.operators import (
            OperatorContext,
            WindowAggOperator,
        )
        from flink_tpu.state.keygroups import hash_keys_to_i64
        from flink_tpu.windowing.assigners import (
            TumblingEventTimeWindows,
        )

        op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                               SumAggregate("v"), "key", capacity=4096)
        op.open(OperatorContext(max_parallelism=128))
        n = 2000
        keys = np.arange(n, dtype=np.int64)
        op.process_batch(RecordBatch.from_pydict({
            "key": keys, KEY_ID_FIELD: hash_keys_to_i64(keys),
            "v": np.ones(n, np.float32),
            TIMESTAMP_FIELD: np.zeros(n, np.int64)}))
        ledger = QuotaLedger(job="sd",
                             quota=TenantQuota(max_resident_rows=512))
        ledger.bind([op])
        assert ledger.resident_rows() >= n  # counted, not 0
        assert ledger.pressure() > 1.0
        # no mesh shed path on this layout: the violation must be LOUD
        assert ledger.enforce() == 0
        assert ledger.quota_violations >= 1


# ------------------------------------------------------------------ fairness


class TestDeficitRoundRobin:
    def test_quantum_shares_and_idle_reset(self):
        drr = DeficitRoundRobin(quantum=100)
        drr.add("a")
        drr.add("b", weight=2.0)
        order = drr.begin_round()
        assert order == ["a", "b"]
        assert drr.deficit("a") == 100 and drr.deficit("b") == 200
        drr.charge("a", 150)
        assert not drr.can_run("a")  # over-quantum job yields
        drr.begin_round()
        assert drr.deficit("a") == 50  # deficit carries (DRR law)
        drr.reset_idle("b")
        assert drr.deficit("b") == 0.0  # empty queue forfeits credit
        drr.charge("a", 0)
        assert drr.deficit("a") == 49  # zero-record step costs a token

    def test_hot_job_cannot_starve_sibling(self):
        """Simulated scheduler: a job with 10x the data still cannot
        take more than ~its share of consecutive service."""
        drr = DeficitRoundRobin(quantum=10)
        drr.add("hot")
        drr.add("cold")
        served = {"hot": 0, "cold": 0}
        for _ in range(50):
            for name in drr.begin_round():
                while drr.can_run(name):
                    served[name] += 1
                    drr.charge(name, 10)
        assert served["hot"] == served["cold"]


# ----------------------------------------------------------------- arbiter


class TestShardArbiter:
    def test_backlog_weighted_allocation_conserves_budget(self):
        arb = ShardArbiter(total_shards=8, cooldown_ticks=0,
                           backlog_norm=1000.0)
        alloc = arb.decide([
            JobDemand(job="hungry", current_shards=2, backlog=7000.0),
            JobDemand(job="quiet", current_shards=2, backlog=0.0),
        ])
        assert alloc["hungry"] > alloc["quiet"] >= 1
        assert alloc["hungry"] + alloc["quiet"] <= 8

    def test_quota_pressure_raises_share(self):
        arb = ShardArbiter(total_shards=8, cooldown_ticks=0)
        base = arb.decide([
            JobDemand(job="a", current_shards=4),
            JobDemand(job="b", current_shards=4),
        ])
        arb2 = ShardArbiter(total_shards=8, cooldown_ticks=0)
        pressured = arb2.decide([
            JobDemand(job="a", current_shards=4, quota_pressure=3.0),
            JobDemand(job="b", current_shards=4),
        ])
        assert pressured["a"] > base["a"]

    def test_clamps_and_floors(self):
        arb = ShardArbiter(total_shards=8, cooldown_ticks=0)
        alloc = arb.decide([
            JobDemand(job="a", current_shards=1, backlog=1e9,
                      max_shards=3),
            JobDemand(job="b", current_shards=1, min_shards=2),
        ])
        assert alloc["a"] <= 3 and alloc["b"] >= 2

    def test_hysteresis_suppresses_one_shard_flap(self):
        arb = ShardArbiter(total_shards=9, hysteresis=1,
                           cooldown_ticks=0)
        alloc = arb.decide([
            JobDemand(job="a", current_shards=4, backlog=100.0),
            JobDemand(job="b", current_shards=4),
        ])
        assert alloc == {"a": 4, "b": 4}

    def test_min_clamps_never_oversubscribe_budget(self):
        """Regression: lo clamps lift low-demand jobs above
        floor(ideal); without a shed pass a 4-shard budget handed out 5
        (a=3 from its near-4.0 ideal, b=c=1 from their floors)."""
        arb = ShardArbiter(total_shards=4, cooldown_ticks=0)
        alloc = arb.decide([
            JobDemand(job="a", current_shards=2, backlog=1e9,
                      min_shards=2),
            JobDemand(job="b", current_shards=1),
            JobDemand(job="c", current_shards=1),
        ])
        assert sum(alloc.values()) <= 4
        # floors still honored while shedding the excess
        assert alloc["a"] >= 2 and alloc["b"] >= 1 and alloc["c"] >= 1

    def test_hysteresis_repin_cannot_oversubscribe(self):
        """Regression: the hysteresis re-pin ran AFTER the budget shed,
        handing pinned jobs back the shards the shed pass took — with
        hysteresis=1 and currents (3,3,3), a (5,2,2) allocation
        re-pinned to (5,3,3)=11 on a 9-shard budget."""
        arb = ShardArbiter(total_shards=9, hysteresis=1,
                           cooldown_ticks=0, backlog_norm=100.0)
        alloc = arb.decide([
            JobDemand(job="a", current_shards=3, backlog=200.0),
            JobDemand(job="b", current_shards=3),
            JobDemand(job="c", current_shards=3),
        ])
        assert sum(alloc.values()) <= 9, alloc


# ------------------------------------------------------------------ cluster


def _pipeline(sink, n=30_000, keys=64, par=2, window=10_000, seed=5,
              extra_config=None):
    cfg = {"execution.micro-batch.size": 2048, "parallelism.default": par}
    cfg.update(extra_config or {})
    env = StreamExecutionEnvironment(Configuration(cfg))
    (env.add_source(DataGenSource(total_records=n, num_keys=keys,
                                  events_per_second_of_eventtime=5_000,
                                  seed=seed),
                    WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key").window(TumblingEventTimeWindows.of(window))
        .sum("value").sink_to(sink))
    return env


def _rows(sink):
    return sorted((r["key"], r["window_end"], r["sum_value"])
                  for r in sink.rows())


class TestSessionCluster:
    def test_two_jobs_oracle_identical_with_fair_interleave(self):
        solo_sink = CollectSink()
        _pipeline(solo_sink).execute("solo")
        solo = _rows(solo_sink)
        sa, sb = CollectSink(), CollectSink()
        cluster = SessionCluster(quantum_records=4096)
        cluster.submit(_pipeline(sa), "job-a")
        cluster.submit(_pipeline(sb), "job-b")
        results = cluster.run(timeout_s=120)
        assert _rows(sa) == solo and _rows(sb) == solo
        assert all(hasattr(r, "metrics") for r in results.values())
        # per-job fairness telemetry exists and registered under the
        # tenancy metric group
        snap = cluster.registry.snapshot() \
            if hasattr(cluster.registry, "snapshot") else None
        for job in cluster.jobs.values():
            assert job.records_total == 30_000
            assert job.busy_ms >= 0.0

    def test_per_job_spill_directories(self, tmp_path):
        """Two jobs sharing one configured state.spill.dir must get
        PRIVATE per-job trees (SpillTier page filenames are per-tier
        sequences — a shared tree would let one job overwrite the
        other's pages)."""
        import os

        base = str(tmp_path / "spill")
        cfg = {"state.slot-table.max-device-slots": 2048,
               "state.spill.dir": base}
        sa, sb = CollectSink(), CollectSink()
        cluster = SessionCluster(quantum_records=4096)
        cluster.submit(_pipeline(sa, extra_config=cfg), "iso-a")
        cluster.submit(_pipeline(sb, extra_config=cfg), "iso-b")
        for name in ("iso-a", "iso-b"):
            dirs = {getattr(e, "_spill_dir", None)
                    for e in cluster.jobs[name].ledger.engines}
            assert dirs == {os.path.join(base, f"job-{name}")}, dirs
        cluster.run(timeout_s=120)
        assert _rows(sa) == _rows(sb) != []

    def test_reused_quota_object_keeps_spill_dirs_private(self, tmp_path):
        """Regression: submit() re-roots quota.spill_dir per job, but it
        used to mutate the CALLER's TenantQuota — one quota object
        reused for two jobs handed job B job A's private spill tree
        (exactly the cross-tenant page overwrite isolation exists to
        prevent). submit must copy the quota, as it copies the config."""
        import os

        base = str(tmp_path / "spill")
        cfg = {"state.slot-table.max-device-slots": 2048,
               "state.spill.dir": base}
        shared = TenantQuota(max_resident_rows=1 << 20)
        sa, sb = CollectSink(), CollectSink()
        cluster = SessionCluster(quantum_records=4096)
        cluster.submit(_pipeline(sa, extra_config=cfg), "share-a",
                       quota=shared)
        cluster.submit(_pipeline(sb, extra_config=cfg), "share-b",
                       quota=shared)
        # the caller's object is untouched; each job got its own tree
        assert shared.spill_dir is None
        for name in ("share-a", "share-b"):
            assert cluster.jobs[name].quota.spill_dir == \
                os.path.join(base, f"job-{name}")
            dirs = {getattr(e, "_spill_dir", None)
                    for e in cluster.jobs[name].ledger.engines}
            assert dirs == {os.path.join(base, f"job-{name}")}, dirs
        cluster.run(timeout_s=120)
        assert _rows(sa) == _rows(sb) != []

    def test_lookup_racing_job_completion_fails_fast(self):
        """A lookup that passes the plane's bound-queue check just as
        the job terminates must get the prompt not-serving error, not a
        dead block until its timeout: _flush re-checks the binding after
        its put and fails everything stranded on the dead queue, and the
        cluster's _finish drains the queue once more after unbinding."""
        import types

        from flink_tpu.cluster.local_executor import (
            StateQueryBatchRequest,
        )
        from flink_tpu.tenancy.serving import ServingPlane

        plane = ServingPlane(timeout_s=5.0)

        class _TerminatingQueue(_q.Queue):
            # the job finishes between the client's bound check and its
            # enqueue landing — the executor's terminal drain missed it
            def put(self, item, *a, **k):
                super().put(item, *a, **k)
                plane.unbind_job("gone")

        plane.bind_job("gone", _TerminatingQueue())
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError, match="not serving"):
            plane._flush("gone", "op", [1, 2], None)
        assert time.perf_counter() - t0 < 1.0

        # cluster side: _finish's drain fails requests already queued
        job = types.SimpleNamespace(control=_q.Queue(), name="dead")
        req = StateQueryBatchRequest("op", [1], None)
        job.control.put(req)
        SessionCluster._fail_stranded_lookups(job)
        with pytest.raises(RuntimeError, match="not serving"):
            req.wait(1.0)

    def test_shared_checkpoint_dir_isolated_per_job(self, tmp_path):
        """Two jobs sharing one configured state.checkpoints.dir must
        checkpoint into PRIVATE per-job trees: chk-N ids are per-storage
        sequences, so a shared tree overwrites — and a crashed job
        would restore whichever job wrote last (cross-tenant state).
        The jobs here have DIFFERENT seeds, so restoring the wrong
        checkpoint diverges from the oracle."""
        import os

        from flink_tpu.connectors.sinks import Sink

        class UpsertSink(Sink):
            def __init__(self):
                self.cells = {}

            def write(self, batch):
                for r in batch.to_rows():
                    self.cells[(r["key"], int(r["window_end"]))] = \
                        float(r["sum_value"])

        solo_a, solo_b = UpsertSink(), UpsertSink()
        _pipeline(solo_a, n=20_000).execute("solo-a")
        _pipeline(solo_b, n=20_000, seed=9).execute("solo-b")
        assert solo_a.cells != solo_b.cells
        ck = str(tmp_path / "ck")
        cfg = {"state.checkpoints.dir": ck,
               "execution.checkpointing.every-n-source-batches": 2}
        sa, sb = UpsertSink(), UpsertSink()
        cluster = SessionCluster(quantum_records=1024, max_restarts=2)
        cluster.submit(_pipeline(sa, n=20_000, extra_config=cfg),
                       "steady")
        cluster.submit(_pipeline(sb, n=20_000, seed=9,
                                 extra_config=cfg), "crashy")
        plan = FaultPlan(rules=[FaultRule(pattern="task.batch", nth=5,
                                          where={"job": "crashy"})])
        with chaos.chaos_active(plan, seed=11):
            results = cluster.run(timeout_s=180)
        assert cluster.jobs["crashy"].restarts == 1
        assert not isinstance(results["crashy"], BaseException)
        assert sa.cells == solo_a.cells
        assert sb.cells == solo_b.cells
        assert sorted(os.listdir(ck)) == ["job-crashy", "job-steady"]

    def test_serving_plane_coalesces_concurrent_lookups(self):
        """Client threads fire point lookups against a running job; the
        plane coalesces them into device batches (batches < lookups)
        and every result matches a direct engine read."""
        sink = CollectSink()
        env = _pipeline(sink, n=120_000, keys=16, window=1 << 40)
        cluster = SessionCluster(quantum_records=2048)
        cluster.submit(env, "serve-job")
        errors = []
        got = {}

        def client(tid):
            try:
                # wait until state exists, then hammer
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    out = cluster.lookup("serve-job",
                                         "window_agg(SumAggregate)",
                                         tid % 16)
                    if out:
                        got[tid] = out
                        return
                    time.sleep(0.01)
                errors.append(f"client {tid}: no state observed")
            except RuntimeError:
                pass  # job finished while we were querying: benign
            except BaseException as e:  # noqa: BLE001
                errors.append(f"client {tid}: {e!r}")

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        cluster.run(timeout_s=120)
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        m = cluster.serving.metrics()
        assert m["lookups_total"] >= len(got) > 0
        assert m["lookup_batches_total"] <= m["lookups_total"]
        for tid, out in got.items():
            (ns, cols), = out.items()
            assert cols["sum_value"] > 0

    def test_packed_lookup_batch_matches_dict_path(self):
        """r19 fast path, end-to-end through the cluster: packed batch
        lookups against a REPLICA-armed running job materialize
        bit-identical to the dict path, and the native probe table
        actually served (when the library is available)."""
        from flink_tpu.tenancy.serving import PackedLookupResult

        sink = CollectSink()
        env = _pipeline(sink, n=120_000, keys=16, window=1 << 40,
                        extra_config={
                            "serving.replica": True,
                            "serving.replica.publish-interval-ms": 5})
        cluster = SessionCluster(quantum_records=2048)
        cluster.submit(env, "packed-job")
        errors = []
        checked = []

        def client():
            try:
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    keys = list(range(16))
                    packed = cluster.lookup_batch_packed(
                        "packed-job", "window_agg(SumAggregate)", keys)
                    assert isinstance(packed, PackedLookupResult)
                    if any(packed.to_dicts()):
                        # the dict path a moment later may see a newer
                        # boundary; only a repeated mismatch counts
                        for _ in range(5):
                            dicts = cluster.lookup_batch(
                                "packed-job",
                                "window_agg(SumAggregate)", keys)
                            if packed == dicts:
                                checked.append(True)
                                return
                            packed = cluster.lookup_batch_packed(
                                "packed-job",
                                "window_agg(SumAggregate)", keys)
                        errors.append("packed != dict results")
                        return
                    time.sleep(0.01)
            except RuntimeError:
                pass  # job finished while we were querying: benign
            except BaseException as e:  # noqa: BLE001
                errors.append(f"packed client: {e!r}")

        t = threading.Thread(target=client)
        t.start()
        cluster.run(timeout_s=120)
        t.join(timeout=30)
        assert not errors, errors
        # the cross-check must have actually RUN (a client that never
        # observed state — or always-empty packed results — would pass
        # vacuously otherwise)
        assert checked, "packed-vs-dict cross-check never executed"
        from flink_tpu.native import hotcache_available
        from flink_tpu.tenancy.hot_cache import HotRowCache

        if hotcache_available():
            assert not isinstance(cluster.serving.hot_cache,
                                  HotRowCache)

    def test_one_job_crash_restarts_from_checkpoint_sibling_unharmed(
            self, tmp_path):
        """task.batch crash in job B: B restores from its checkpoint and
        finishes; job A never notices. Oracle-identity for B's sink is
        asserted via the upsert model (replayed fires land on the same
        window cells)."""
        from flink_tpu.connectors.sinks import Sink

        class UpsertSink(Sink):
            def __init__(self):
                self.cells = {}

            def write(self, batch):
                for r in batch.to_rows():
                    self.cells[(r["key"], int(r["window_end"]))] = \
                        float(r["sum_value"])

        solo = UpsertSink()
        _pipeline(solo, n=20_000).execute("solo")
        sa, sb = UpsertSink(), UpsertSink()
        ck = str(tmp_path / "ck-b")
        cluster = SessionCluster(quantum_records=1024, max_restarts=2)
        cluster.submit(_pipeline(sa, n=20_000), "steady")
        cluster.submit(_pipeline(sb, n=20_000, extra_config={
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 2}),
            "crashy")
        plan = FaultPlan(rules=[FaultRule(pattern="task.batch", nth=5,
                                          where={"job": "crashy"})])
        with chaos.chaos_active(plan, seed=11):
            results = cluster.run(timeout_s=180)
        assert cluster.jobs["crashy"].restarts == 1
        assert cluster.jobs["steady"].restarts == 0
        assert not isinstance(results["steady"], BaseException)
        assert not isinstance(results["crashy"], BaseException)
        assert sa.cells == solo.cells
        assert sb.cells == solo.cells
        # the restore was from a real checkpoint, not a vacuous cold
        # restart (the dir is re-rooted per job by submit)
        import os

        chks = os.listdir(os.path.join(ck, "job-crashy"))
        assert any(d.startswith("chk-") for d in chks), chks

    def test_failed_restart_contained_sibling_survives(self):
        """Regression: a restart that ITSELF raised (unreadable
        checkpoint tree, operator open failure) escaped _on_failure
        through step_round and killed every sibling. It must charge
        the restart budget and fail only that job."""
        solo = CollectSink()
        _pipeline(solo, n=20_000).execute("solo")
        want = _rows(solo)
        sa, sb = CollectSink(), CollectSink()
        cluster = SessionCluster(quantum_records=1024, max_restarts=2)
        cluster.submit(_pipeline(sa, n=20_000), "steady")
        cluster.submit(_pipeline(sb, n=20_000, seed=9), "doomed")
        real_start = cluster._start

        def start(job, restore_from=None):
            if job.name == "doomed" and job.restarts > 0:
                raise RuntimeError("operator open failed")
            return real_start(job, restore_from=restore_from)

        cluster._start = start
        plan = FaultPlan(rules=[FaultRule(pattern="task.batch", nth=3,
                                          where={"job": "doomed"})])
        with chaos.chaos_active(plan, seed=7):
            results = cluster.run(timeout_s=120)
        assert _rows(sa) == want  # sibling finished oracle-identical
        assert isinstance(results["doomed"], BaseException)
        assert cluster.jobs["doomed"].restarts == 2  # budget consumed
        assert not isinstance(results["steady"], BaseException)

    def test_finished_jobs_release_execution_resources(self):
        """Regression: _finish kept TenantJob.handle (operator graph ->
        engines -> device planes) and the per-job gauges alive forever
        — one dead job's working set per HISTORICAL job on a long-lived
        cluster. Terminal jobs must drop both; cheap counters stay."""
        sa, sb = CollectSink(), CollectSink()
        cluster = SessionCluster(quantum_records=4096)
        cluster.submit(_pipeline(sa, n=20_000), "gone-a")
        cluster.submit(_pipeline(sb, n=20_000, seed=9), "gone-b")
        cluster.run(timeout_s=120)
        for j in cluster.jobs.values():
            assert j.handle is None and j.gen is None
            assert len(j.ledger.engines) == 0
            assert j.records_total == 20_000  # counters survive
        snap = cluster.registry.snapshot()
        assert not any(".gone-a." in k or ".gone-b." in k
                       for k in snap), "per-job gauges not unregistered"
        assert any(k.endswith("tenancy.jobs_live") for k in snap)

    def test_arbiter_live_rescale_preserves_oracle_identity(self):
        """A fixed-decision arbiter forces a live 2->4 / 2->1 rescale on
        running jobs; outputs must stay oracle-identical (the PR 4
        key-group migration, driven cross-job)."""

        class FixedArbiter:
            def __init__(self):
                self.calls = 0

            def decide(self, demands, dead_shards=0):
                self.calls += 1
                want = {"grow": 4, "shrink": 1}
                return {d.job: want[d.job] for d in demands}

        solo_sink = CollectSink()
        _pipeline(solo_sink, n=60_000).execute("solo")
        solo = _rows(solo_sink)
        sa, sb = CollectSink(), CollectSink()
        arb = FixedArbiter()
        cluster = SessionCluster(quantum_records=1024, arbiter=arb,
                                 arbitrate_every_s=0.01)
        cluster.submit(_pipeline(sa, n=60_000), "grow")
        cluster.submit(_pipeline(sb, n=60_000), "shrink")
        # grab the engines pre-run: terminal jobs RELEASE their handle
        # (test_finished_jobs_release_execution_resources), so post-run
        # inspection must go through refs taken while the jobs ran
        eng_a = cluster.jobs["grow"].handle.stateful_operators()[
            0].windower
        eng_b = cluster.jobs["shrink"].handle.stateful_operators()[
            0].windower
        cluster.run(timeout_s=180)
        assert arb.calls >= 1
        assert eng_a.reshards_completed >= 1
        assert eng_b.reshards_completed >= 1
        assert int(eng_a.P) == 4
        assert int(eng_b.P) == 1
        assert _rows(sa) == solo and _rows(sb) == solo


# ----------------------------------------------------------------- serving


class TestLookupCoalescer:
    def test_concurrent_lookups_share_flushes(self):
        flushes = []
        gate = threading.Event()

        def flush(keys, ns):
            gate.wait(5)
            flushes.append(list(keys))
            return [k * 10 for k in keys]

        co = LookupCoalescer(flush, max_batch=64, window_ms=30.0)
        results = {}

        def worker(k):
            results[k] = co.lookup(k)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        gate.set()
        for t in threads:
            t.join(timeout=10)
        assert results == {i: i * 10 for i in range(8)}
        assert co.lookups_total == 8

    def test_short_flush_reply_errors_every_rider(self):
        """Regression: a flush returning fewer results than keys left
        the tail riders result=None with no error — indistinguishable
        from 'key has no state'. A short reply must raise to ALL riders
        of the batch."""
        co = LookupCoalescer(lambda keys, ns: [], max_batch=8,
                             window_ms=0.0)
        with pytest.raises(RuntimeError, match="returned 0 results"):
            co.lookup(7)
        assert co.batches_total < 8  # amortization happened
        assert co.p99_ms() >= 0.0

    def test_flush_error_fans_out_and_recovers(self):
        calls = {"n": 0}

        def flush(keys, ns):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom")
            return list(keys)

        co = LookupCoalescer(flush, window_ms=0.0)
        with pytest.raises(RuntimeError):
            co.lookup(1)
        assert co.lookup(2) == 2  # coalescer survives a failed batch

    def test_stats_scrape_during_concurrent_lookups(self):
        """A metrics scrape must not crash while client threads serve:
        the reservoir deques and counters are read under the coalescer
        lock (iterating a deque mid-append raises RuntimeError)."""
        from flink_tpu.tenancy.serving import aggregate_lookup_stats

        co = LookupCoalescer(lambda keys, ns: [0.0] * len(keys),
                             max_batch=8, window_ms=0.0)
        stop = threading.Event()
        errs = []

        def hammer():
            while not stop.is_set():
                co.lookup(1)

        def scrape():
            try:
                while not stop.is_set():
                    s = aggregate_lookup_stats([co])
                    assert s["lookups_total"] >= s[
                        "lookup_batches_total"]
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = ([threading.Thread(target=hammer) for _ in range(4)]
                   + [threading.Thread(target=scrape)])
        for t in threads:
            t.start()
        time.sleep(0.3)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errs, errs
        assert co.stats_snapshot()[0] > 0


class TestServingPlaneRetirement:
    def test_unbind_retires_coalescers_and_keeps_totals(self):
        """Regression: finished jobs' coalescers were never removed, so
        a cluster churning many short jobs grew the map (and every
        scrape's walk, and the latency reservoirs) per HISTORICAL job.
        Retirement must keep the cumulative gauges monotonic."""
        import queue

        from flink_tpu.tenancy.serving import ServingPlane

        plane = ServingPlane(window_ms=0.0)
        for i in range(5):
            name = f"job-{i}"
            plane.bind_job(name, queue.Queue())
            plane._coalescer(name, "op").note_batch(4, 1.0)
            plane.unbind_job(name)
        assert len(plane._pool) == 0  # nothing accumulates
        assert plane.lookups_total() == 20
        assert plane.lookup_batches_total() == 5
        m = plane.metrics()
        assert m["lookups_total"] == 20
        assert m["lookup_batches_total"] == 5
        assert m["lookup_p99_ms"] >= 1.0  # reservoirs retired too


class TestShardArbiterCooldown:
    def test_cooldown_suppresses_exactly_n_ticks(self):
        arb = ShardArbiter(total_shards=8, cooldown_ticks=2,
                           backlog_norm=1000.0)
        demands = [
            JobDemand(job="hungry", current_shards=2, backlog=7000.0),
            JobDemand(job="quiet", current_shards=2),
        ]
        first = arb.decide(demands)  # first tick may act
        assert first["hungry"] > first["quiet"]
        moved = [JobDemand(job="hungry", current_shards=first["hungry"]),
                 JobDemand(job="quiet", current_shards=first["quiet"],
                           backlog=7000.0)]
        hold = {d.job: d.current_shards for d in moved}
        # exactly cooldown_ticks=2 quiet ticks after a reallocation...
        assert arb.decide(moved) == hold
        assert arb.decide(moved) == hold
        # ...then the arbiter acts again
        third = arb.decide(moved)
        assert third["quiet"] > third["hungry"]


# -------------------------------------------------------------------- chaos


def _chaos_steps(seed, n_steps=8, batch=256, keys=120):
    rng = np.random.default_rng(seed)
    steps = []
    t = 0
    for i in range(n_steps):
        ks = rng.integers(0, keys, batch)
        vs = np.ones(batch, dtype=np.float32)
        ts = t + np.sort(rng.integers(0, 400, batch))
        t += 700
        steps.append((ks, vs, ts, t - 2 * GAP))
    return steps


class TestTwoJobChaos:
    def _makers(self):
        mesh = make_mesh(2)

        def mk_mesh():
            return MeshSessionEngine(GAP, SumAggregate("v"), mesh,
                                     capacity_per_shard=1024,
                                     max_device_slots=1024)

        def mk_oracle():
            from flink_tpu.windowing.sessions import SessionWindower

            return SessionWindower(GAP, SumAggregate("v"),
                                   capacity=1 << 15)

        return mk_mesh, mk_oracle

    def test_crash_mid_serving_burst_restores_jobs_independently(
            self, tmp_path):
        """Job B crashes (session-fire fault) while both jobs serve
        batched lookups; B restores from ITS checkpoint, A never
        restores, both end oracle-identical."""
        mk_mesh, mk_oracle = self._makers()
        plan = FaultPlan(rules=[
            FaultRule(pattern="mesh.session_fire", nth=6),
        ])
        reports = run_crash_restore_verify_multi(
            make_engines={"a": mk_mesh, "b": mk_mesh},
            make_oracles={"a": mk_oracle, "b": mk_oracle},
            steps_by_job={"a": _chaos_steps(1), "b": _chaos_steps(2)},
            plan=plan, seed=5, ckpt_root=str(tmp_path),
            checkpoint_every=2,
            serve_keys={"a": [1, 2, 3], "b": [4, 5, 6]})
        crashed = sorted(j for j, r in reports.items() if r.crashes)
        assert len(crashed) == 1  # exactly one job took the fault
        other = "a" if crashed == ["b"] else "b"
        assert reports[crashed[0]].restores >= 1
        assert reports[other].restores == 0
        for r in reports.values():
            assert not r.diverged

    def test_serving_lookup_fault_retries_without_corruption(
            self, tmp_path):
        """A recoverable serving.lookup fault at the real injection site
        retries in place: lookups recover, no crash, no divergence."""
        mk_mesh, mk_oracle = self._makers()
        plan = FaultPlan(rules=[
            FaultRule(pattern="serving.lookup", nth=2,
                      recoverable=True),
        ])
        reports = run_crash_restore_verify_multi(
            make_engines={"a": mk_mesh, "b": mk_mesh},
            make_oracles={"a": mk_oracle, "b": mk_oracle},
            steps_by_job={"a": _chaos_steps(3), "b": _chaos_steps(4)},
            plan=plan, seed=9, ckpt_root=str(tmp_path),
            serve_keys={"a": [1, 2], "b": [3, 4]})
        total_faults = sum(
            r.faults_injected.get("serving.lookup", 0)
            for r in reports.values())
        assert total_faults >= 1
        assert sum(r.retries for r in reports.values()) >= 1
        assert sum(r.recoveries for r in reports.values()) >= 1
        # per-job attribution: the fault landed in exactly one job's
        # serving burst, and only that job's report carries it
        carrying = [j for j, r in reports.items()
                    if r.faults_injected.get("serving.lookup", 0)]
        assert len(carrying) == 1
        for r in reports.values():
            assert r.crashes == 0 and not r.diverged

    def test_torn_checkpoint_skip_counted_per_job(self, tmp_path):
        """Regression: the multi-job restore path dropped the
        single-job harness's corrupt_checkpoints_skipped accounting.
        Tear job a's first checkpoint (rename durable, bytes not),
        then crash a — job-targeted serving fault — before its next
        good one: a's restore must fall past the torn snapshot AND
        count the skip; b's report stays clean."""
        mk_mesh, mk_oracle = self._makers()
        plan = FaultPlan(rules=[
            # first checkpoint write overall is job a's chk-1 (jobs
            # step round-robin, a first)
            FaultRule(pattern="checkpoint.write.torn", nth=1,
                      kind="drop"),
            # crash a at its 3rd serving burst (pos 3: after torn
            # chk-1, before chk-2) — a non-recoverable raise
            # propagates through run_recoverable as the crash path
            FaultRule(pattern="serving.lookup", nth=3,
                      where={"job": "a"}),
        ])
        reports = run_crash_restore_verify_multi(
            make_engines={"a": mk_mesh, "b": mk_mesh},
            make_oracles={"a": mk_oracle, "b": mk_oracle},
            steps_by_job={"a": _chaos_steps(5), "b": _chaos_steps(6)},
            plan=plan, seed=13, ckpt_root=str(tmp_path),
            checkpoint_every=2,
            serve_keys={"a": [1, 2], "b": [3, 4]})
        ra, rb = reports["a"], reports["b"]
        assert ra.faults_injected.get("checkpoint.write.torn", 0) == 1
        assert ra.crashes == 1
        assert ra.corrupt_checkpoints_skipped >= 1
        assert ra.cold_restarts == 1  # the only checkpoint was torn
        assert rb.crashes == 0
        assert rb.corrupt_checkpoints_skipped == 0
        # points_hit is attributed per job like faults_injected: a
        # replayed after its cold restart, so it performed strictly
        # more checkpoint writes than b (the old global copy made both
        # reports claim the identical union)
        assert ra.points_hit.get("checkpoint.write", 0) > \
            rb.points_hit.get("checkpoint.write", 0)
        for r in reports.values():
            assert not r.diverged

    def test_serving_lookup_fault_via_executor_control_plane(self):
        """The OTHER real site: LocalExecutor._serve_query wraps the
        batched lookup in run_recoverable — an injected transient fault
        retries and the caller still gets correct values."""
        sink = CollectSink()
        env = _pipeline(sink, n=60_000, keys=8, window=1 << 40)
        cluster = SessionCluster(quantum_records=1024)
        cluster.submit(env, "qs")
        # job-targeted: the executor's fault ctx must carry job= or this
        # where filter can never match and the plan silently no-ops
        plan = FaultPlan(rules=[
            FaultRule(pattern="serving.lookup", nth=1,
                      recoverable=True, where={"job": "qs"})])
        got = {}

        def client():
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                try:
                    out = cluster.lookup_batch(
                        "qs", "window_agg(SumAggregate)", [3, 5])
                except RuntimeError:
                    return
                if all(out):
                    got["out"] = out
                    return
                time.sleep(0.01)

        with chaos.chaos_active(plan, seed=1) as ctl:
            t = threading.Thread(target=client)
            t.start()
            cluster.run(timeout_s=120)
            t.join(timeout=30)
            assert ctl.faults_injected.get("serving.lookup", 0) >= 1
            assert ctl.retries >= 1
        assert "out" in got
        for per_key in got["out"]:
            (ns, cols), = per_key.items()
            assert cols["sum_value"] > 0
