"""Mesh-sharded session windows (MeshSessionEngine) vs the single-device
engine and the brute-force oracle, including cross-engine snapshot restore
and the public-API path (parallelism=N session job through env.execute) —
the BASELINE.json session-clickstream config, scaled to CI."""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.windowing.aggregates import CountAggregate, SumAggregate
from flink_tpu.windowing.sessions import SessionWindower

from tests.test_sessions import fired_to_dict, keyed_batch, oracle_sessions


def _random_events(n=6000, keys=300, seed=11, spread=40_000, gap=100,
                   skew=2000):
    """Events in roughly time order with bounded out-of-orderness
    (arrival ts jitters by <= skew), so a lagging watermark never drops
    records — required for oracle equality under mid-stream fires."""
    rng = np.random.default_rng(seed)
    ks = rng.integers(0, keys, n)
    base = np.sort(rng.integers(0, spread, n))
    ts = np.maximum(base - rng.integers(0, skew, n), 0)
    vs = rng.random(n).astype(np.float32) * 5
    return list(zip(ks.tolist(), vs.tolist(), ts.tolist()))


def _drive(engine, events, batch_size=500, wm_every=4):
    """Feed events in arrival order with periodic watermarks (max seen ts
    lags by one batch to allow out-of-orderness), then final flush."""
    fired = []
    max_ts = 0
    for i in range(0, len(events), batch_size):
        chunk = events[i:i + batch_size]
        ks = [e[0] for e in chunk]
        vs = [e[1] for e in chunk]
        ts = [e[2] for e in chunk]
        engine.process_batch(keyed_batch(ks, vs, ts))
        max_ts = max([max_ts] + ts)
        if (i // batch_size) % wm_every == wm_every - 1:
            fired.extend(engine.on_watermark(max_ts - 5000))
    fired.extend(engine.on_watermark(1 << 60))
    return fired


class TestMeshSessionEngine:
    def test_matches_oracle_and_single_device(self, eight_device_mesh):
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

        gap = 100
        events = _random_events(gap=gap)
        mesh_eng = MeshSessionEngine(gap, SumAggregate("v"),
                                     eight_device_mesh,
                                     capacity_per_shard=4096)
        single = SessionWindower(gap, SumAggregate("v"), capacity=16384)
        fired_mesh = fired_to_dict(_drive(mesh_eng, events))
        fired_single = fired_to_dict(_drive(single, events))
        oracle = oracle_sessions(events, gap)
        assert set(fired_mesh) == set(oracle)
        for k, want in oracle.items():
            assert fired_mesh[k] == pytest.approx(want, rel=1e-4), k
        assert fired_mesh.keys() == fired_single.keys()

    def test_cross_batch_merge_on_mesh(self, eight_device_mesh):
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

        eng = MeshSessionEngine(100, SumAggregate("v"), eight_device_mesh,
                                capacity_per_shard=1024)
        # two separated sessions for one key, then a bridge record merges
        # them (cross-batch, exercises the sharded merge kernel)
        eng.process_batch(keyed_batch([7, 7], [1.0, 2.0], [0, 180]))
        eng.process_batch(keyed_batch([7], [4.0], [90]))
        fired = fired_to_dict(eng.on_watermark(1 << 60))
        assert fired == {(7, 0, 280): pytest.approx(7.0)}

    def test_high_cardinality_many_shards(self, eight_device_mesh):
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

        # BASELINE.json row 5 scaled down: many distinct keys, one session
        # each (high-cardinality keyed state)
        n = 20_000
        eng = MeshSessionEngine(50, CountAggregate(), eight_device_mesh,
                                capacity_per_shard=8192)
        ks = np.arange(n, dtype=np.int64)
        ts = np.zeros(n, dtype=np.int64)
        eng.process_batch(RecordBatch.from_pydict(
            {KEY_ID_FIELD: ks, "v": np.ones(n, dtype=np.float32)},
            timestamps=ts))
        fired = _sum_counts(eng.on_watermark(1 << 60))
        assert fired == n

    def test_snapshot_restore_mesh_to_mesh(self, eight_device_mesh):
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

        gap = 100
        events = _random_events(n=2000, keys=100)
        cut = len(events) // 2
        a = MeshSessionEngine(gap, SumAggregate("v"), eight_device_mesh,
                              capacity_per_shard=4096)
        for i in range(0, cut, 400):
            chunk = events[i:min(i + 400, cut)]
            a.process_batch(keyed_batch([e[0] for e in chunk],
                                        [e[1] for e in chunk],
                                        [e[2] for e in chunk]))
        snap = a.snapshot()
        b = MeshSessionEngine(gap, SumAggregate("v"), eight_device_mesh,
                              capacity_per_shard=4096)
        b.restore(snap)
        for i in range(cut, len(events), 400):
            chunk = events[i:i + 400]
            b.process_batch(keyed_batch([e[0] for e in chunk],
                                        [e[1] for e in chunk],
                                        [e[2] for e in chunk]))
        fired = fired_to_dict(b.on_watermark(1 << 60))
        oracle = oracle_sessions(events, gap)
        assert fired.keys() == oracle.keys()
        for k, want in oracle.items():
            assert fired[k] == pytest.approx(want, rel=1e-4), k

    def test_snapshot_restore_cross_engine(self, eight_device_mesh):
        """single-device snapshot -> mesh engine and back."""
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

        gap = 100
        events = _random_events(n=1500, keys=80)
        cut = len(events) // 2
        single = SessionWindower(gap, SumAggregate("v"), capacity=8192)
        for i in range(0, cut, 300):
            chunk = events[i:min(i + 300, cut)]
            single.process_batch(keyed_batch([e[0] for e in chunk],
                                             [e[1] for e in chunk],
                                             [e[2] for e in chunk]))
        snap = single.snapshot()
        mesh_eng = MeshSessionEngine(gap, SumAggregate("v"),
                                     eight_device_mesh,
                                     capacity_per_shard=4096)
        mesh_eng.restore(snap)
        for i in range(cut, len(events), 300):
            chunk = events[i:i + 300]
            mesh_eng.process_batch(keyed_batch([e[0] for e in chunk],
                                               [e[1] for e in chunk],
                                               [e[2] for e in chunk]))
        # mesh -> single again before the flush
        snap2 = mesh_eng.snapshot()
        single2 = SessionWindower(gap, SumAggregate("v"), capacity=8192)
        single2.restore(snap2)
        fired = fired_to_dict(single2.on_watermark(1 << 60))
        oracle = oracle_sessions(events, gap)
        assert fired.keys() == oracle.keys()
        for k, want in oracle.items():
            assert fired[k] == pytest.approx(want, rel=1e-4), k

    def test_delta_snapshot_tombstones_absorbed_sessions(
            self, eight_device_mesh):
        """A session absorbed by a merge AFTER the delta base must ship a
        freed-namespace tombstone, or restore resurrects an orphan row."""
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

        eng = MeshSessionEngine(100, SumAggregate("v"), eight_device_mesh,
                                capacity_per_shard=1024)
        eng.process_batch(keyed_batch([7, 7], [1.0, 2.0], [0, 180]))
        eng.snapshot()  # full base
        eng.process_batch(keyed_batch([7], [4.0], [90]))  # bridge merges
        delta = eng.snapshot(mode="delta")
        freed = set(np.asarray(delta["table"]["freed_namespaces"]).tolist())
        live_sids = {iv[2] for iv in eng.meta.sessions[7]}
        assert len(live_sids) == 1
        # exactly the absorbed sid is tombstoned
        assert freed, "absorbed session must leave a tombstone"
        assert freed.isdisjoint(live_sids)

    def test_query_sessions(self, eight_device_mesh):
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

        eng = MeshSessionEngine(100, SumAggregate("v"), eight_device_mesh,
                                capacity_per_shard=1024)
        eng.process_batch(keyed_batch([3, 3, 9], [1.0, 2.0, 5.0],
                                      [0, 50, 1000]))
        got = eng.query_sessions(3)
        assert got == {150: {"sum_v": pytest.approx(3.0)}}
        assert eng.query_sessions(9) == {1100: {"sum_v": pytest.approx(5.0)}}
        assert eng.query_sessions(12345) == {}


def _sum_counts(batches):
    return int(sum(b["count"].sum() for b in batches))


class TestMeshSessionPublicApi:
    def test_session_job_parallelism_8(self):
        """BASELINE session-clickstream config (scaled): session windows at
        parallelism=8 through env.execute, vs the oracle."""
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import Source
        from flink_tpu.runtime.watermarks import WatermarkStrategy
        from flink_tpu.windowing.assigners import EventTimeSessionWindows

        gap = 100
        events = _random_events(n=5000, keys=200, spread=20_000, gap=gap)

        class ListSource(Source):
            def __init__(self, rows):
                self.rows = rows
                self.pos = 0

            def poll_batch(self, max_records):
                if self.pos >= len(self.rows):
                    return None
                chunk = self.rows[self.pos:self.pos + max_records]
                self.pos += len(chunk)
                return RecordBatch.from_pydict(
                    {"user": np.asarray([e[0] for e in chunk],
                                        dtype=np.int64),
                     "v": np.asarray([e[1] for e in chunk],
                                     dtype=np.float32)},
                    timestamps=[e[2] for e in chunk])

            def snapshot_position(self):
                return self.pos

            def restore_position(self, pos):
                self.pos = pos

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "parallelism.default": 8,
            "state.slot-table.capacity": 4096,
        }))
        sink = CollectSink()
        env.add_source(ListSource(events),
                       WatermarkStrategy.for_bounded_out_of_orderness(5000)) \
            .key_by("user") \
            .window(EventTimeSessionWindows.with_gap(gap)) \
            .sum("v").sink_to(sink)
        env.execute("mesh-sessions")
        got = {(r["user"], r["window_start"], r["window_end"]):
               r["sum_v"] for r in sink.rows()}
        oracle = {k: v for k, v in
                  oracle_sessions(events, gap).items()}
        assert got.keys() == oracle.keys()
        for k, want in oracle.items():
            assert got[k] == pytest.approx(want, rel=1e-4), k
