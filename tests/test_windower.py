"""Windower semantics vs a pure-Python oracle.

Mirrors the role of the reference's WindowOperatorTest
(flink-streaming-java/src/test/.../windowing/WindowOperatorTest.java): drive
the operator with records + watermarks, assert fired window contents.
"""

import collections

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.windowing.aggregates import CountAggregate, SumAggregate
from flink_tpu.windowing.assigners import (
    CumulativeEventTimeWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.windowing.windower import (
    WINDOW_END_FIELD,
    WINDOW_START_FIELD,
    SliceSharedWindower,
)


def keyed_batch(keys, values, ts):
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
         "v": np.asarray(values, dtype=np.float32)},
        timestamps=ts)


def oracle_windows(assigner, events, watermark):
    """events: list of (key, value, ts). Returns {(key, wstart, wend): sum}
    for every window with end-1 <= watermark containing data."""
    out = collections.defaultdict(float)
    for key, value, ts in events:
        se = int(assigner.assign_slice_ends(np.array([ts]))[0])
        for wend in assigner.window_ends_for_slice(se):
            if wend - 1 <= watermark:
                out[(key, assigner.window_start(wend), wend)] += value
    return dict(out)


def fired_to_dict(batches, field="sum_v"):
    out = {}
    for b in batches:
        for row in b.to_rows():
            out[(row[KEY_ID_FIELD], row[WINDOW_START_FIELD],
                 row[WINDOW_END_FIELD])] = row[field]
    return out


class TestTumbling:
    def test_basic_fire(self):
        assigner = TumblingEventTimeWindows.of(1000)
        w = SliceSharedWindower(assigner, SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1, 1, 2], [1, 2, 5], [100, 900, 500]))
        assert w.on_watermark(500) == []  # window [0,1000) not complete
        fired = w.on_watermark(999)
        got = fired_to_dict(fired)
        assert got == {(1, 0, 1000): 3.0, (2, 0, 1000): 5.0}
        # firing again emits nothing
        assert w.on_watermark(1500) == []

    def test_multiple_windows_in_order(self):
        assigner = TumblingEventTimeWindows.of(100)
        w = SliceSharedWindower(assigner, SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1, 1, 1], [1, 2, 4], [50, 150, 250]))
        fired = w.on_watermark(300)
        got = fired_to_dict(fired)
        assert got == {(1, 0, 100): 1.0, (1, 100, 200): 2.0, (1, 200, 300): 4.0}
        ends = [b[WINDOW_END_FIELD][0] for b in fired]
        assert ends == sorted(ends)

    def test_late_records_dropped(self):
        assigner = TumblingEventTimeWindows.of(100)
        w = SliceSharedWindower(assigner, SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1], [1], [50]))
        w.on_watermark(99)
        w.process_batch(keyed_batch([1], [100], [10]))  # late for [0,100)
        assert w.late_records_dropped == 1
        assert w.on_watermark(199) == []

    def test_state_freed_after_fire(self):
        assigner = TumblingEventTimeWindows.of(100)
        w = SliceSharedWindower(assigner, SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1, 2, 3], [1, 1, 1], [10, 20, 30]))
        assert w.table.num_used == 3
        w.on_watermark(99)
        assert w.table.num_used == 0


class TestSliding:
    def test_hop_slice_sharing(self):
        # size 300, slide 100 -> 3 slices per window
        assigner = SlidingEventTimeWindows.of(300, 100)
        assert assigner.slices_per_window == 3
        w = SliceSharedWindower(assigner, SumAggregate("v"), capacity=1024)
        events = [(1, 1.0, 50), (1, 2.0, 150), (1, 4.0, 250), (2, 10.0, 150)]
        for k, v, t in events:
            w.process_batch(keyed_batch([k], [v], [t]))
        wm = 599
        fired = fired_to_dict(w.on_watermark(wm))
        assert fired == oracle_windows(assigner, events, wm)

    def test_hop_against_oracle_random(self):
        rng = np.random.default_rng(42)
        assigner = SlidingEventTimeWindows.of(500, 100)
        w = SliceSharedWindower(assigner, SumAggregate("v"), capacity=4096)
        events = []
        wm = -1
        all_fired = {}
        for step in range(10):
            n = 200
            keys = rng.integers(0, 20, n)
            vals = rng.random(n).astype(np.float32)
            # monotonically advancing time region per step
            ts = rng.integers(step * 300, step * 300 + 600, n)
            for k, v, t in zip(keys.tolist(), vals.tolist(), ts.tolist()):
                events.append((k, v, t))
            w.process_batch(keyed_batch(keys, vals, ts))
            wm = step * 300
            all_fired.update(fired_to_dict(w.on_watermark(wm)))
        all_fired.update(fired_to_dict(w.on_watermark(10**9)))
        # oracle ignores lateness; replicate drop-late semantics by replaying
        oracle = {}
        w2_max_fired = -1
        max_fired = -1
        fired_so_far = set()
        # simpler: compare only windows fired after final flush vs oracle with
        # late-drop simulation
        oracle = oracle_with_lateness(assigner, events_by_step(events, 10), wm_schedule(10))
        assert set(all_fired) == set(oracle)
        for kk in oracle:
            assert all_fired[kk] == pytest.approx(oracle[kk], rel=1e-5)


def events_by_step(events, steps):
    # events were appended in step order, 200 per step
    return [events[i * 200:(i + 1) * 200] for i in range(steps)]


def wm_schedule(steps):
    return [s * 300 for s in range(steps)] + [10**9]


def oracle_with_lateness(assigner, step_events, watermarks):
    """Replay with drop-late semantics: record dropped iff its slice's last
    window end <= max fired end at arrival time."""
    contrib = collections.defaultdict(float)
    fired = {}
    max_fired = -(1 << 62)
    pending = set()

    def fire_up_to(wm):
        nonlocal max_fired
        for wend in sorted(pending):
            if wend - 1 <= wm:
                pending.discard(wend)
                rows = {}
                for (key, we), v in contrib.items():
                    if we == wend:
                        rows[key] = rows.get(key, 0.0) + v
                for key, v in rows.items():
                    fired[(key, assigner.window_start(wend), wend)] = v
                max_fired = max(max_fired, wend)

    wm_i = 0
    for step, events in enumerate(step_events):
        for key, value, ts in events:
            se = int(assigner.assign_slice_ends(np.array([ts]))[0])
            ends = assigner.window_ends_for_slice(se)
            if ends[-1] <= max_fired:
                continue  # late
            for wend in ends:
                if wend > max_fired:
                    contrib[(key, wend)] += value
                    pending.add(wend)
        fire_up_to(watermarks[step])
    fire_up_to(watermarks[-1])
    return fired


class TestCumulate:
    def test_cumulate(self):
        assigner = CumulativeEventTimeWindows(max_size_ms=300, step_ms=100)
        w = SliceSharedWindower(assigner, CountAggregate(), capacity=1024)
        w.process_batch(keyed_batch([1, 1, 1], [1, 1, 1], [50, 150, 250]))
        fired = fired_to_dict(w.on_watermark(299), field="count")
        # windows (0,100]:1, (0,200]:2, (0,300]:3
        assert fired == {(1, 0, 100): 1, (1, 0, 200): 2, (1, 0, 300): 3}


class TestSnapshotRestore:
    def test_windower_snapshot_restore(self):
        assigner = SlidingEventTimeWindows.of(300, 100)
        events1 = [(1, 1.0, 50), (2, 2.0, 150)]
        events2 = [(1, 4.0, 250)]

        w = SliceSharedWindower(assigner, SumAggregate("v"), capacity=1024)
        for k, v, t in events1:
            w.process_batch(keyed_batch([k], [v], [t]))
        snap = w.snapshot()

        w2 = SliceSharedWindower(assigner, SumAggregate("v"), capacity=1024)
        w2.restore(snap)
        for k, v, t in events2:
            w2.process_batch(keyed_batch([k], [v], [t]))
        fired = fired_to_dict(w2.on_watermark(10**9))

        oracle = oracle_windows(assigner, events1 + events2, 10**9)
        assert fired == pytest.approx(oracle)
